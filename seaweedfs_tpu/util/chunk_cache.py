"""Tiered chunk cache: bounded in-memory LRU + size-tiered on-disk layers.

Behavioral port of `weed/util/chunk_cache/chunk_cache.go:13,30`: reads
through the filer/mount keep recently used chunks in RAM and spill larger /
older ones to disk, tiered by chunk size so huge chunks do not evict many
small ones. The reference backs disk tiers with needle volumes; here each
tier is a directory of files with an LRU index — same bounds, simpler
machinery (no volume GC needed since chunks are immutable and keyed by fid).
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict


class MemChunkCache:
    """Bytes-bounded LRU (`chunk_cache_in_memory.go`)."""

    def __init__(self, limit_bytes: int = 64 * 1024 * 1024) -> None:
        self.limit = limit_bytes
        self._used = 0
        self._map: OrderedDict[str, bytes] = OrderedDict()
        self._lock = threading.Lock()

    def get(self, key: str) -> bytes | None:
        with self._lock:
            data = self._map.get(key)
            if data is not None:
                self._map.move_to_end(key)
            return data

    def set(self, key: str, data: bytes) -> None:
        if len(data) > self.limit:
            return
        with self._lock:
            old = self._map.pop(key, None)
            if old is not None:
                self._used -= len(old)
            self._map[key] = data
            self._used += len(data)
            while self._used > self.limit:
                _, evicted = self._map.popitem(last=False)
                self._used -= len(evicted)

    def clear(self) -> None:
        with self._lock:
            self._map.clear()
            self._used = 0


class DiskCacheLayer:
    """One on-disk tier: files under dir, LRU-evicted to stay under limit."""

    def __init__(self, dir_: str, limit_bytes: int) -> None:
        self.dir = dir_
        self.limit = limit_bytes
        os.makedirs(dir_, exist_ok=True)
        self._lock = threading.Lock()
        self._index: OrderedDict[str, int] = OrderedDict()  # key -> size
        self._used = 0
        for name in os.listdir(dir_):
            p = os.path.join(dir_, name)
            if name.endswith(".tmp"):  # crashed mid-set(); unservable
                try:
                    os.remove(p)
                except OSError:
                    pass
                continue
            if os.path.isfile(p):
                sz = os.path.getsize(p)
                self._index[name] = sz
                self._used += sz

    @staticmethod
    def _fname(key: str) -> str:
        return hashlib.sha1(key.encode()).hexdigest()

    def get(self, key: str) -> bytes | None:
        name = self._fname(key)
        with self._lock:
            if name not in self._index:
                return None
            self._index.move_to_end(name)
        try:
            with open(os.path.join(self.dir, name), "rb") as f:
                return f.read()
        except OSError:
            return None

    def set(self, key: str, data: bytes) -> None:
        if len(data) > self.limit:
            return
        name = self._fname(key)
        tmp = os.path.join(self.dir, name + ".tmp")
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, os.path.join(self.dir, name))
        with self._lock:
            old = self._index.pop(name, None)
            if old is not None:
                self._used -= old
            self._index[name] = len(data)
            self._used += len(data)
            while self._used > self.limit:
                victim, sz = self._index.popitem(last=False)
                self._used -= sz
                try:
                    os.remove(os.path.join(self.dir, victim))
                except OSError:
                    pass


# tier split thresholds (chunk_cache.go: onDiskCacheSizeLimit0/1)
SMALL_LIMIT = 256 * 1024
MEDIUM_LIMIT = 1024 * 1024


class TieredChunkCache:
    """Mem for hot small chunks; disk tiers by size class (`chunk_cache.go:30`
    NewTieredChunkCache)."""

    def __init__(self, mem_limit: int = 64 * 1024 * 1024,
                 disk_dir: str | None = None,
                 disk_limit: int = 1024 * 1024 * 1024) -> None:
        self.mem = MemChunkCache(mem_limit)
        self.disks: list[tuple[int, DiskCacheLayer]] = []
        if disk_dir:
            # small/medium/large tiers split the budget 1:2:5 like the
            # reference's default volume-count ratios
            for name, limit, share in (
                ("small", SMALL_LIMIT, 0.125),
                ("medium", MEDIUM_LIMIT, 0.25),
                ("large", 1 << 62, 0.625),
            ):
                self.disks.append(
                    (limit, DiskCacheLayer(os.path.join(disk_dir, name),
                                           max(1, int(disk_limit * share))))
                )

    def get_chunk(self, file_id: str) -> bytes | None:
        data = self.mem.get(file_id)
        if data is not None:
            return data
        for _, layer in self.disks:
            data = layer.get(file_id)
            if data is not None:
                self.mem.set(file_id, data)
                return data
        return None

    def set_chunk(self, file_id: str, data: bytes) -> None:
        self.mem.set(file_id, data)
        for limit, layer in self.disks:
            if len(data) <= limit:
                layer.set(file_id, data)
                return
