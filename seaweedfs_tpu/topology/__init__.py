"""Master-side cluster state (reference: `weed/topology/`).

Tree DataCenter -> Rack -> DataNode with free-slot accounting, per-
(collection, replica placement, ttl) volume layouts with writable tracking,
replica-placement-aware volume growth, and heartbeat-driven sync. Pure state
machine — proven by synthetic heartbeats exactly like the reference's
topology tests (SURVEY.md §4 "in-process cluster simulation").
"""

from .node import DataCenter, DataNode, Rack
from .topology import Topology
from .volume_layout import VolumeLayout

__all__ = ["DataCenter", "DataNode", "Rack", "Topology", "VolumeLayout"]
