"""Replica-placement-aware volume growth (reference:
`weed/topology/volume_growth.go:42-300`).

Given an xyz replica placement, pick servers for one new volume's replicas:
a main DC with rp.diff_rack+1 eligible racks, a main rack with
rp.same_rack+1 eligible nodes, plus "other" racks/DCs — every picked node
needs a free slot. Randomized among eligible candidates (the reference
weights by free space; uniform random keeps the same invariants)."""

from __future__ import annotations

import random

from seaweedfs_tpu.storage.types import ReplicaPlacement

from .node import DataCenter, DataNode, Rack


class NoFreeSpace(Exception):
    pass


def find_empty_slots(
    data_centers: dict[str, DataCenter],
    rp: ReplicaPlacement,
    preferred_dc: str = "",
    rng: random.Random | None = None,
) -> list[DataNode]:
    """Nodes for one volume's rp.copy_count() replicas
    (`volume_growth.go:145` findEmptySlotsForOneVolume)."""
    rng = rng or random
    # main DC: needs rp.diff_rack_count+1 racks with capacity, plus
    # rp.diff_data_center_count other DCs with >= 1 slot
    main_dc_candidates = []
    for dc in data_centers.values():
        if preferred_dc and dc.name != preferred_dc:
            continue
        eligible_racks = [
            r for r in dc.racks.values() if _rack_eligible(r, rp)
        ]
        if len(eligible_racks) >= rp.diff_rack_count + 1:
            main_dc_candidates.append((dc, eligible_racks))
    if not main_dc_candidates:
        raise NoFreeSpace(
            f"no data center can host rp={rp} (preferred={preferred_dc or 'any'})"
        )
    other_dcs_needed = rp.diff_data_center_count
    for dc, eligible_racks in rng.sample(
        main_dc_candidates, len(main_dc_candidates)
    ):
        others = [
            d for d in data_centers.values()
            if d.name != dc.name and d.free_slots() >= 1
        ]
        if len(others) < other_dcs_needed:
            continue
        try:
            return _pick_in_dc(dc, eligible_racks, rp, rng) + [
                _pick_any_node(d, rng) for d in rng.sample(others, other_dcs_needed)
            ]
        except NoFreeSpace:
            continue
    raise NoFreeSpace(f"not enough data centers for rp={rp}")


def _rack_eligible(rack: Rack, rp: ReplicaPlacement) -> bool:
    nodes = [n for n in rack.nodes.values() if n.free_slots() >= 1]
    return len(nodes) >= rp.same_rack_count + 1


def _pick_in_dc(
    dc: DataCenter, eligible_racks: list[Rack], rp: ReplicaPlacement, rng
) -> list[DataNode]:
    for main_rack in rng.sample(eligible_racks, len(eligible_racks)):
        other_racks = [
            r for r in dc.racks.values()
            if r.name != main_rack.name and r.free_slots() >= 1
        ]
        if len(other_racks) < rp.diff_rack_count:
            continue
        nodes = [n for n in main_rack.nodes.values() if n.free_slots() >= 1]
        if len(nodes) < rp.same_rack_count + 1:
            continue
        picked = rng.sample(nodes, rp.same_rack_count + 1)
        picked += [
            _pick_any_node_in_rack(r, rng)
            for r in rng.sample(other_racks, rp.diff_rack_count)
        ]
        return picked
    raise NoFreeSpace(f"no rack in dc {dc.name} can host rp={rp}")


def _pick_any_node_in_rack(rack: Rack, rng) -> DataNode:
    nodes = [n for n in rack.nodes.values() if n.free_slots() >= 1]
    if not nodes:
        raise NoFreeSpace(f"rack {rack.name} has no free slots")
    return rng.choice(nodes)


def _pick_any_node(dc: DataCenter, rng) -> DataNode:
    racks = [r for r in dc.racks.values() if r.free_slots() >= 1]
    if not racks:
        raise NoFreeSpace(f"dc {dc.name} has no free slots")
    return _pick_any_node_in_rack(rng.choice(racks), rng)


def targets_per_growth(rp: ReplicaPlacement) -> int:
    """How many volumes to grow at once per replication level
    (`volume_growth.go:42-49` VolumeGrowStrategy)."""
    copies = rp.copy_count()
    if copies == 1:
        return 7
    if copies == 2:
        return 6
    if copies == 3:
        return 3
    return 1
