"""Topology tree nodes (reference: `weed/topology/node.go`, `data_node.go`,
`rack.go`, `data_center.go`)."""

from __future__ import annotations

import time
from dataclasses import dataclass, field


@dataclass
class VolumeInfo:
    """Master's view of one volume replica (master_pb VolumeInformationMessage)."""

    id: int
    collection: str = ""
    size: int = 0
    file_count: int = 0
    delete_count: int = 0
    deleted_byte_count: int = 0
    read_only: bool = False
    replica_placement: int = 0
    ttl: int = 0
    version: int = 3
    # volume streams its appends through the online RS encoder: its
    # durability is local-dat + parity shards, not replica fan-out
    ec_online: bool = False
    # missing-or-torn parity shards the holder audited against its
    # durable watermark — >0 means this LIVE online volume's redundancy
    # is damaged and an online ec_rebuild (re-arm + re-encode) is due
    ec_online_parity_damaged: int = 0
    # order-independent live-needle-set digest (anti-entropy): replica
    # holders reporting different digests for one volume have silently
    # diverged — the scrub detector re-syncs from the majority holder
    needle_digest: str = ""
    # cumulative native-op counters carried on the beat (PR 16): the
    # master's heat rollup differentiates consecutive beats into
    # per-collection/per-node access rates
    read_ops: int = 0
    write_ops: int = 0
    read_bytes: int = 0
    write_bytes: int = 0

    @staticmethod
    def from_dict(d: dict) -> "VolumeInfo":
        return VolumeInfo(
            id=int(d["id"]),
            collection=d.get("collection", ""),
            size=int(d.get("size", 0)),
            file_count=int(d.get("file_count", 0)),
            delete_count=int(d.get("delete_count", 0)),
            deleted_byte_count=int(d.get("deleted_byte_count", 0)),
            read_only=bool(d.get("read_only", False)),
            replica_placement=int(d.get("replica_placement", 0)),
            ttl=int(d.get("ttl", 0)),
            version=int(d.get("version", 3)),
            ec_online=bool(d.get("ec_online", False)),
            ec_online_parity_damaged=int(
                d.get("ec_online_parity_damaged", 0)
            ),
            needle_digest=str(d.get("needle_digest", "")),
            read_ops=int(d.get("read_ops", 0)),
            write_ops=int(d.get("write_ops", 0)),
            read_bytes=int(d.get("read_bytes", 0)),
            write_bytes=int(d.get("write_bytes", 0)),
        )


@dataclass
class EcShardInfo:
    id: int
    collection: str = ""
    ec_index_bits: int = 0

    def shard_ids(self) -> list[int]:
        return [i for i in range(14) if self.ec_index_bits & (1 << i)]


@dataclass
class DataNode:
    ip: str
    port: int
    public_url: str = ""
    max_volume_count: int = 100
    rack: "Rack | None" = None
    volumes: dict[int, VolumeInfo] = field(default_factory=dict)
    ec_shards: dict[int, EcShardInfo] = field(default_factory=dict)
    last_seen: float = field(default_factory=time.time)
    max_file_key: int = 0
    # unresolved scrub findings the node's last heartbeat carried
    # (maintenance/scrub.py detect() turns them into repair tasks)
    scrub_findings: list = field(default_factory=list)
    # volumes a scrub pass on this node holds right now: vacuum defers
    # their compaction (heartbeat-fed, maintenance/scrub.py)
    scrub_active: set = field(default_factory=set)

    @property
    def id(self) -> str:
        return f"{self.ip}:{self.port}"

    @property
    def url(self) -> str:
        return self.public_url or self.id

    def free_slots(self) -> int:
        ec_slots = sum(
            (len(s.shard_ids()) + 13) // 14 for s in self.ec_shards.values()
        )
        return self.max_volume_count - len(self.volumes) - ec_slots

    def dc_name(self) -> str:
        return self.rack.data_center.name if self.rack else ""

    def rack_name(self) -> str:
        return self.rack.name if self.rack else ""


@dataclass
class Rack:
    name: str
    data_center: "DataCenter"
    nodes: dict[str, DataNode] = field(default_factory=dict)

    def get_or_create_node(
        self, ip: str, port: int, public_url: str = "", max_volume_count: int = 100
    ) -> DataNode:
        key = f"{ip}:{port}"
        node = self.nodes.get(key)
        if node is None:
            node = DataNode(
                ip=ip, port=port, public_url=public_url,
                max_volume_count=max_volume_count, rack=self,
            )
            self.nodes[key] = node
        node.public_url = public_url or node.public_url
        if max_volume_count:
            node.max_volume_count = max_volume_count
        return node

    def free_slots(self) -> int:
        return sum(n.free_slots() for n in self.nodes.values())


@dataclass
class DataCenter:
    name: str
    racks: dict[str, Rack] = field(default_factory=dict)

    def get_or_create_rack(self, name: str) -> Rack:
        rack = self.racks.get(name)
        if rack is None:
            rack = Rack(name=name, data_center=self)
            self.racks[name] = rack
        return rack

    def free_slots(self) -> int:
        return sum(r.free_slots() for r in self.racks.values())
