"""VolumeLayout: writable-volume tracking per (collection, rp, ttl)
(reference: `weed/topology/volume_layout.go:108,290`)."""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field

from seaweedfs_tpu.storage.types import ReplicaPlacement

from .node import DataNode, VolumeInfo


class NoWritableVolume(Exception):
    pass


@dataclass
class VolumeLayout:
    replica_placement: ReplicaPlacement
    ttl_u32: int
    volume_size_limit: int = 30 * 1024 * 1024 * 1024
    locations: dict[int, list[DataNode]] = field(default_factory=dict)
    writables: set[int] = field(default_factory=set)
    readonly: set[int] = field(default_factory=set)
    oversized: set[int] = field(default_factory=set)
    # volumes whose heartbeat reports online-EC: durability is parity,
    # not replicas — one live holder is a full complement
    ec_online: set[int] = field(default_factory=set)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def register_volume(self, v: VolumeInfo, node: DataNode) -> None:
        with self._lock:
            locs = self.locations.setdefault(v.id, [])
            if node not in locs:
                locs.append(node)
            if v.read_only:
                self.readonly.add(v.id)
            else:
                self.readonly.discard(v.id)
            if v.ec_online:
                self.ec_online.add(v.id)
            else:
                self.ec_online.discard(v.id)  # fell back to replication
            if v.size >= self.volume_size_limit:
                self.oversized.add(v.id)
            else:
                self.oversized.discard(v.id)  # vacuum shrank it back
            self._refresh_writable(v.id)

    def unregister_volume(self, vid: int, node: DataNode) -> None:
        with self._lock:
            locs = self.locations.get(vid, [])
            if node in locs:
                locs.remove(node)
            if not locs:
                self.locations.pop(vid, None)
                self.writables.discard(vid)
                self.readonly.discard(vid)
                self.oversized.discard(vid)
                self.ec_online.discard(vid)
            else:
                self._refresh_writable(vid)

    def _required_copies(self, vid: int) -> int:
        """Online-EC volumes ack on local durability + parity emit: one
        live holder is a full complement regardless of the placement's
        replica demand (the parity shards are the redundancy)."""
        if vid in self.ec_online:
            return 1
        return self.replica_placement.copy_count()

    def _refresh_writable(self, vid: int) -> None:
        """Writable iff full replica count present, not oversized, not RO
        (`volume_layout.go:enoughCopies`)."""
        locs = self.locations.get(vid, [])
        ok = (
            len(locs) >= self._required_copies(vid)
            and vid not in self.readonly
            and vid not in self.oversized
        )
        if ok:
            self.writables.add(vid)
        else:
            self.writables.discard(vid)

    def pick_for_write(
        self, data_center: str = "",
        shard: tuple[int, int] | None = None,
    ) -> tuple[int, list[DataNode]]:
        """Random writable volume, optionally constrained to a DC
        (`volume_layout.go:290` PickForWrite). `shard=(i, n)` prefers
        vids where vid % n == i — the gateway lease-pool vid-space
        partition. The constraint is SOFT: an empty slice falls back to
        the whole writable set (a small cluster must still assign), so
        it removes contention when volumes are plentiful and costs
        nothing when they are not."""
        with self._lock:
            candidates = list(self.writables)
            if data_center:
                candidates = [
                    vid
                    for vid in candidates
                    if any(
                        n.dc_name() == data_center for n in self.locations[vid]
                    )
                ]
            if shard is not None and shard[1] > 1:
                sliced = [vid for vid in candidates
                          if vid % shard[1] == shard[0]]
                if sliced:
                    candidates = sliced
            if not candidates:
                raise NoWritableVolume(
                    f"no writable volumes (rp={self.replica_placement}, "
                    f"dc={data_center or 'any'})"
                )
            vid = random.choice(candidates)
            return vid, list(self.locations[vid])

    def lookup(self, vid: int) -> list[DataNode]:
        return list(self.locations.get(vid, []))

    def set_oversized_if(self, vid: int, size: int) -> None:
        if size >= self.volume_size_limit:
            with self._lock:
                self.oversized.add(vid)
                self._refresh_writable(vid)

    def under_replicated(self) -> list[tuple[int, int]]:
        """[(vid, live replica count)] for volumes with fewer live replicas
        than the placement demands — the master-side health view that
        `SeaweedFS_master_volumes_underreplicated` and `cluster.check`
        render (`volume_layout.go` enoughCopies, inverted)."""
        with self._lock:
            return sorted(
                (vid, len(locs))
                for vid, locs in self.locations.items()
                if len(locs) < self._required_copies(vid)
            )

    def active_volume_count(self, data_center: str = "") -> int:
        if not data_center:
            return len(self.writables)
        return sum(
            1
            for vid in self.writables
            if any(n.dc_name() == data_center for n in self.locations.get(vid, []))
        )

    def volume_ids(self) -> list[int]:
        return sorted(self.locations)
