"""Topology: the master's cluster state machine (reference:
`weed/topology/topology.go:29-300`, `topology_event_handling.go`).

Fed by volume-server heartbeats; answers assign/lookup; grows volumes when a
layout runs out of writable space; expires dead nodes.
"""

from __future__ import annotations

import random
import threading
import time

from seaweedfs_tpu.storage.types import TTL, ReplicaPlacement

from .node import DataCenter, DataNode, EcShardInfo, VolumeInfo
from .sequence import MemorySequencer
from .volume_growth import find_empty_slots, targets_per_growth
from .volume_layout import NoWritableVolume, VolumeLayout


class Topology:
    def __init__(
        self,
        volume_size_limit: int = 30 * 1024 * 1024 * 1024,
        pulse_seconds: int = 5,
        sequencer: MemorySequencer | None = None,
    ) -> None:
        self.data_centers: dict[str, DataCenter] = {}
        self.volume_size_limit = volume_size_limit
        self.pulse_seconds = pulse_seconds
        self.sequencer = sequencer or MemorySequencer()
        self._layouts: dict[tuple[str, int, int], VolumeLayout] = {}
        self._max_volume_id = 0
        self.vid_allocator = None  # raft propose hook (set by MasterServer)
        self._lock = threading.Lock()
        # ec shard map: vid -> {shard_id -> [DataNode]}
        self.ec_shards: dict[int, dict[int, list[DataNode]]] = {}
        self.ec_collections: dict[int, str] = {}

    # --- structure ------------------------------------------------------------
    def get_or_create_dc(self, name: str) -> DataCenter:
        with self._lock:
            dc = self.data_centers.get(name)
            if dc is None:
                dc = DataCenter(name=name)
                self.data_centers[name] = dc
            return dc

    def layout(
        self, collection: str, rp: ReplicaPlacement, ttl_u32: int = 0
    ) -> VolumeLayout:
        key = (collection, rp.to_byte(), ttl_u32)
        with self._lock:
            lo = self._layouts.get(key)
            if lo is None:
                lo = VolumeLayout(
                    replica_placement=rp,
                    ttl_u32=ttl_u32,
                    volume_size_limit=self.volume_size_limit,
                )
                self._layouts[key] = lo
            return lo

    def all_nodes(self) -> list[DataNode]:
        out = []
        for dc in self.data_centers.values():
            for rack in dc.racks.values():
                out.extend(rack.nodes.values())
        return out

    def find_node(self, node_id: str) -> DataNode | None:
        for n in self.all_nodes():
            if n.id == node_id:
                return n
        return None

    # --- heartbeats -----------------------------------------------------------
    def sync_heartbeat(
        self,
        hb: dict,
        dc_name: str = "DefaultDataCenter",
        rack_name: str = "DefaultRack",
    ) -> DataNode:
        """Full-state heartbeat ingest (`master_grpc_server.go:62` SendHeartbeat
        — incremental deltas can layer on later; full sync is idempotent)."""
        dc = self.get_or_create_dc(hb.get("data_center") or dc_name)
        rack = dc.get_or_create_rack(hb.get("rack") or rack_name)
        node = rack.get_or_create_node(
            hb["ip"],
            int(hb["port"]),
            hb.get("public_url", ""),
            int(hb.get("max_volume_count", 100)),
        )
        node.last_seen = time.time()
        node.max_file_key = int(hb.get("max_file_key", 0))
        node.scrub_findings = list(hb.get("scrub_findings", []))
        node.scrub_active = {int(v) for v in hb.get("scrub_active", [])}
        self.sequencer.set_max(node.max_file_key)

        new_volumes = {int(v["id"]): VolumeInfo.from_dict(v) for v in hb.get("volumes", [])}
        # unregister volumes that disappeared
        for vid in list(node.volumes):
            if vid not in new_volumes:
                self._unregister_volume(node.volumes[vid], node)
        for vid, info in new_volumes.items():
            self._register_volume(info, node)
        node.volumes = new_volumes

        # ec shards
        new_ec = {
            int(s["id"]): EcShardInfo(
                id=int(s["id"]),
                collection=s.get("collection", ""),
                ec_index_bits=int(s.get("ec_index_bits", 0)),
            )
            for s in hb.get("ec_shards", [])
        }
        for vid in list(node.ec_shards):
            if vid not in new_ec:
                self._unregister_ec(vid, node)
        for vid, info in new_ec.items():
            # unregister-then-register: a node reporting the SAME ec volume
            # with FEWER shards (partial shard loss/move) must drop out of
            # the shard ids it no longer holds, or ec_missing_shards() keeps
            # counting the stale holder and the loss stays invisible
            if vid in node.ec_shards:
                self._unregister_ec(vid, node)
            self._register_ec(info, node)
        node.ec_shards = new_ec
        return node

    def _register_volume(self, v: VolumeInfo, node: DataNode) -> None:
        with self._lock:
            self._max_volume_id = max(self._max_volume_id, v.id)
        rp = ReplicaPlacement.from_byte(v.replica_placement)
        self.layout(v.collection, rp, v.ttl).register_volume(v, node)

    def _unregister_volume(self, v: VolumeInfo, node: DataNode) -> None:
        rp = ReplicaPlacement.from_byte(v.replica_placement)
        self.layout(v.collection, rp, v.ttl).unregister_volume(v.id, node)

    def _register_ec(self, info: EcShardInfo, node: DataNode) -> None:
        with self._lock:
            shard_map = self.ec_shards.setdefault(info.id, {})
            self.ec_collections[info.id] = info.collection
            for sid in info.shard_ids():
                nodes = shard_map.setdefault(sid, [])
                if node not in nodes:
                    nodes.append(node)

    def _unregister_ec(self, vid: int, node: DataNode) -> None:
        with self._lock:
            shard_map = self.ec_shards.get(vid, {})
            for sid in list(shard_map):
                if node in shard_map[sid]:
                    shard_map[sid].remove(node)
                if not shard_map[sid]:
                    del shard_map[sid]
            if not shard_map:
                self.ec_shards.pop(vid, None)
                self.ec_collections.pop(vid, None)

    def expire_dead_nodes(self, timeout_factor: float = 5.0) -> list[DataNode]:
        """Drop nodes silent for timeout_factor x pulse
        (`topology_event_handling.go`)."""
        cutoff = time.time() - timeout_factor * self.pulse_seconds
        dead = []
        for dc in self.data_centers.values():
            for rack in dc.racks.values():
                for key in list(rack.nodes):
                    node = rack.nodes[key]
                    if node.last_seen < cutoff:
                        for v in node.volumes.values():
                            self._unregister_volume(v, node)
                        for vid in list(node.ec_shards):
                            self._unregister_ec(vid, node)
                        del rack.nodes[key]
                        dead.append(node)
        return dead

    # --- assign / lookup --------------------------------------------------------
    def next_volume_id(self) -> int:
        # under raft the id allocation is a replicated command so every
        # master agrees (`master_grpc_server_raft.go`); vid_allocator is the
        # leader's propose hook, and the raft apply path calls
        # _next_volume_id_raw on every node
        if self.vid_allocator is not None:
            vid = self.vid_allocator()
            with self._lock:
                self._max_volume_id = max(self._max_volume_id, vid)
            return vid
        return self._next_volume_id_raw()

    def _next_volume_id_raw(self) -> int:
        with self._lock:
            self._max_volume_id += 1
            return self._max_volume_id

    def pick_for_write(
        self,
        count: int = 1,
        replication: str = "000",
        ttl: str = "",
        collection: str = "",
        data_center: str = "",
        shard: tuple[int, int] | None = None,
    ) -> tuple[str, int, list[DataNode]]:
        """-> (fid, count, replica locations) (`topology.go:248` PickForWrite).
        `shard=(i, n)` soft-constrains the pick to vids in a gateway's
        lease slice (vid % n == i) — see VolumeLayout.pick_for_write."""
        rp = ReplicaPlacement.parse(replication)
        ttl_u32 = TTL.parse(ttl).to_u32()
        lo = self.layout(collection, rp, ttl_u32)
        # no auto-grow here: growth requires contacting volume servers, which
        # is the master server's job (`MasterServer._grow_volumes`)
        vid, nodes = lo.pick_for_write(data_center, shard=shard)
        key = self.sequencer.next_file_id(count)
        cookie = random.randint(0, 0xFFFFFFFF)
        from seaweedfs_tpu.storage.file_id import format_needle_id_cookie

        fid = f"{vid},{format_needle_id_cookie(key, cookie)}"
        return fid, count, nodes

    def grow(
        self,
        collection: str,
        rp: ReplicaPlacement,
        ttl_u32: int,
        data_center: str = "",
        target_count: int | None = None,
    ) -> list[tuple[int, list[DataNode]]]:
        """Allocate new volumes on picked servers (`volume_growth.go:243`).
        Returns [(vid, nodes)] — the caller (master server) instructs the
        volume servers to actually create them."""
        n = target_count or targets_per_growth(rp)
        grown = []
        for _ in range(n):
            try:
                nodes = find_empty_slots(self.data_centers, rp, data_center)
            except Exception:
                break
            vid = self.next_volume_id()
            grown.append((vid, nodes))
        if not grown:
            raise NoWritableVolume(
                f"failed to grow any volume for rp={rp} dc={data_center or 'any'}"
            )
        return grown

    def lookup(self, vid: int, collection: str = "") -> list[DataNode]:
        for (coll, _, _), lo in list(self._layouts.items()):
            if collection and coll != collection:
                continue
            nodes = lo.lookup(vid)
            if nodes:
                return nodes
        # EC volumes: any node holding any shard can serve reads
        shard_map = self.ec_shards.get(vid)
        if shard_map:
            seen: list[DataNode] = []
            for nodes in shard_map.values():
                for n in nodes:
                    if n not in seen:
                        seen.append(n)
            return seen
        return []

    def lookup_ec_shards(self, vid: int) -> dict[int, list[DataNode]] | None:
        return self.ec_shards.get(vid)

    # --- stats -----------------------------------------------------------------
    def under_replicated_volumes(self) -> list[tuple[str, int, int, int]]:
        """[(collection, vid, have, want)] across every layout — volumes
        whose live replica count is below their placement's demand."""
        with self._lock:
            layouts = list(self._layouts.items())
        out = []
        for (coll, _, _), lo in layouts:
            want = lo.replica_placement.copy_count()
            for vid, have in lo.under_replicated():
                out.append((coll, vid, have, want))
        return sorted(out, key=lambda t: (t[0], t[1]))

    def vacuum_candidates(
        self, garbage_threshold: float
    ) -> list[tuple[DataNode, int, float]]:
        """[(node, vid, garbage_ratio)] for writable, non-empty volumes whose
        deleted-bytes share crosses the threshold — the master's vacuum scan
        and the maintenance vacuum detector share this one view
        (`topology_vacuum.go:216` scanning semantics)."""
        out = []
        for node in self.all_nodes():
            held = getattr(node, "scrub_active", ())
            for vid, info in list(node.volumes.items()):
                if info.size == 0 or info.read_only:
                    continue
                if vid in held:
                    # a scrub pass holds this volume: compacting now
                    # would swap (nm, dat) under the scanner — wasting
                    # the pass at best, fabricating suspects at worst.
                    # The pass moves on within a beat or two; the
                    # garbage is still there next scan.
                    continue
                if info.ec_online:
                    # compaction rewrites every .dat offset and discards
                    # the streamed parity (vacuum_reset); online volumes
                    # reclaim garbage at seal time instead
                    continue
                ratio = info.deleted_byte_count / max(info.size, 1)
                if ratio > garbage_threshold:
                    out.append((node, vid, ratio))
        return out

    def ec_online_volumes(self) -> set[int]:
        """Volume ids whose latest heartbeat reports online-EC mode —
        parity-only durability by design, never an under-replication
        fault (maintenance detectors consult this)."""
        out: set[int] = set()
        with self._lock:
            layouts = list(self._layouts.values())
        for lo in layouts:
            with lo._lock:  # heartbeats mutate the set concurrently
                out |= lo.ec_online
        return out

    def ec_missing_shards(self) -> dict[int, int]:
        """vid -> number of EC shards with NO live holder."""
        from seaweedfs_tpu.storage.erasure_coding import geometry

        total = geometry.TOTAL_SHARDS_COUNT
        with self._lock:
            shard_maps = {
                vid: sum(1 for nodes in sm.values() if nodes)
                for vid, sm in self.ec_shards.items()
            }
        return {
            vid: total - present
            for vid, present in shard_maps.items()
            if present < total
        }

    def to_dict(self) -> dict:
        return {
            "max_volume_id": self._max_volume_id,
            "data_centers": [
                {
                    "name": dc.name,
                    "racks": [
                        {
                            "name": rack.name,
                            "nodes": [
                                {
                                    "id": n.id,
                                    "url": n.url,
                                    "volumes": len(n.volumes),
                                    "ec_volumes": len(n.ec_shards),
                                    "max_volume_count": n.max_volume_count,
                                    "volume_infos": [
                                        {
                                            "id": v.id,
                                            "collection": v.collection,
                                            "size": v.size,
                                            "file_count": v.file_count,
                                            "delete_count": v.delete_count,
                                            "garbage": v.deleted_byte_count,
                                            "read_only": v.read_only,
                                            "replica_placement": v.replica_placement,
                                            "ttl": v.ttl,
                                            "ec_online": v.ec_online,
                                            "ec_online_parity_damaged":
                                                v.ec_online_parity_damaged,
                                            "needle_digest": v.needle_digest,
                                        }
                                        for v in n.volumes.values()
                                    ],
                                    "ec_shard_infos": [
                                        {
                                            "id": s.id,
                                            "collection": s.collection,
                                            "shards": s.shard_ids(),
                                        }
                                        for s in n.ec_shards.values()
                                    ],
                                }
                                for n in rack.nodes.values()
                            ],
                        }
                        for rack in dc.racks.values()
                    ],
                }
                for dc in self.data_centers.values()
            ],
        }
