"""File-key sequencers (reference: `weed/sequence/sequence.go`,
`snowflake_sequencer.go`)."""

from __future__ import annotations

import json
import os
import threading
import time


class MemorySequencer:
    """Monotonic counter with optional file persistence (the reference
    persists via raft SetMax; a JSON file is this build's single-master WAL)."""

    def __init__(self, state_path: str | None = None, start: int = 1) -> None:
        self._lock = threading.Lock()
        self._path = state_path
        self._counter = start
        if state_path and os.path.exists(state_path):
            with open(state_path) as f:
                self._counter = max(start, int(json.load(f).get("max", start)))

    def next_file_id(self, count: int = 1) -> int:
        with self._lock:
            start = self._counter
            self._counter += count
            self._persist()
            return start

    def set_max(self, seen: int) -> None:
        with self._lock:
            if seen >= self._counter:
                self._counter = seen + 1
                self._persist()

    def peek(self) -> int:
        return self._counter

    def _persist(self) -> None:
        if self._path:
            tmp = self._path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"max": self._counter}, f)
            os.replace(tmp, self._path)


class SnowflakeSequencer:
    """41-bit ms timestamp | 10-bit node id | 12-bit sequence."""

    EPOCH_MS = 1_288_834_974_657

    def __init__(self, node_id: int) -> None:
        self.node_id = node_id & 0x3FF
        self._lock = threading.Lock()
        self._last_ms = 0
        self._seq = 0

    def next_file_id(self, count: int = 1) -> int:
        with self._lock:
            now = int(time.time() * 1000)
            if now == self._last_ms:
                self._seq = (self._seq + 1) & 0xFFF
                if self._seq == 0:
                    while now <= self._last_ms:
                        now = int(time.time() * 1000)
            else:
                self._seq = 0
            self._last_ms = now
            return (
                ((now - self.EPOCH_MS) << 22) | (self.node_id << 12) | self._seq
            )

    def set_max(self, seen: int) -> None:
        pass  # time-ordered; nothing to bump
