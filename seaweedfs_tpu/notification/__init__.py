"""Notification bus: pluggable publish of filer metadata mutations.

Behavioral port of `weed/notification/configuration.go` + per-backend dirs
(log, kafka, aws_sqs, google_pub_sub, gocdk_pub_sub): the filer publishes
each entry mutation as an EventNotification message keyed by its full path;
`weed filer.replicate` consumes the queue and applies events to sinks.

Backends here:
  - `LogQueue` — print-only (reference `notification/log/`)
  - `MemoryQueue` — in-process buffer (tests, embedded replicate loops)
  - `FileQueue` — durable JSON-lines spool directory; a consumer tails it
    (stand-in for kafka/sqs with the same at-least-once contract)
  - `KafkaQueue` — gated on a kafka client being importable (not baked in)

Configured via `configure_notification()` from `notification.toml`'s
equivalent (`[notification.<kind>] enabled=true`).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable


class NotificationQueue:
    kind = "none"

    def send_message(self, key: str, message: dict) -> None:
        raise NotImplementedError


class LogQueue(NotificationQueue):
    kind = "log"

    def __init__(self, printer: Callable[[str], None] | None = None) -> None:
        from seaweedfs_tpu.util.glog import v

        self._print = printer or (lambda s: v(1, s))

    def send_message(self, key: str, message: dict) -> None:
        self._print(f"notify {key}: {json.dumps(message)[:200]}")


class MemoryQueue(NotificationQueue):
    kind = "memory"

    def __init__(self) -> None:
        self.messages: list[tuple[str, dict]] = []
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)

    def send_message(self, key: str, message: dict) -> None:
        with self._cond:
            self.messages.append((key, message))
            self._cond.notify_all()

    def poll(self, start: int = 0, timeout: float = 0.0) -> list[tuple[str, dict]]:
        with self._cond:
            if len(self.messages) <= start and timeout > 0:
                self._cond.wait(timeout)
            return self.messages[start:]


class FileQueue(NotificationQueue):
    """Append-only JSON-lines spool, one file per day; `read_from(offset)`
    lets a consumer resume from a byte cursor (at-least-once)."""

    kind = "file"

    def __init__(self, spool_dir: str) -> None:
        self.dir = spool_dir
        os.makedirs(spool_dir, exist_ok=True)
        self._lock = threading.Lock()

    def _spool_path(self) -> str:
        return os.path.join(
            self.dir, time.strftime("%Y-%m-%d", time.gmtime()) + ".jsonl"
        )

    def send_message(self, key: str, message: dict) -> None:
        line = json.dumps({"key": key, "message": message}) + "\n"
        with self._lock:
            with open(self._spool_path(), "a") as f:
                f.write(line)

    def read_all(self) -> list[tuple[str, dict]]:
        out: list[tuple[str, dict]] = []
        for name in sorted(os.listdir(self.dir)):
            if not name.endswith(".jsonl"):
                continue
            with open(os.path.join(self.dir, name)) as f:
                for line in f:
                    if line.strip():
                        d = json.loads(line)
                        out.append((d["key"], d["message"]))
        return out


class KafkaQueue(NotificationQueue):
    kind = "kafka"

    def __init__(self, hosts: list[str], topic: str, producer=None) -> None:
        self.topic = topic
        if producer is not None:
            self._producer = producer  # injected (contract tests use a fake)
            return
        try:
            from kafka import KafkaProducer
        except ImportError as e:
            raise RuntimeError(
                "kafka notification backend requires kafka-python"
            ) from e
        self._producer = KafkaProducer(bootstrap_servers=hosts)

    def send_message(self, key: str, message: dict) -> None:
        self._producer.send(
            self.topic, key=key.encode(), value=json.dumps(message).encode()
        )


def configure_notification(kind: str, **opts) -> NotificationQueue:
    if kind == "log":
        return LogQueue()
    if kind == "memory":
        return MemoryQueue()
    if kind == "file":
        return FileQueue(opts["spool_dir"])
    if kind == "kafka":
        return KafkaQueue(opts["hosts"], opts["topic"])  # pragma: no cover
    if kind == "aws_sqs":
        from .cloud import SqsQueue

        return SqsQueue(
            opts.get("access_key", ""), opts.get("secret_key", ""),
            opts.get("region", "us-east-1"), opts["queue_name"],
            endpoint=opts.get("endpoint"),
        )
    if kind == "google_pub_sub":
        from .cloud import GooglePubSubQueue

        provider = opts.get("token_provider")
        if provider is None and opts.get("google_application_credentials"):
            # config files can only carry strings: build the OAuth2 provider
            # from the service-account key path, like the reference's
            # google_application_credentials option
            from seaweedfs_tpu.replication.cloud_sinks import (
                service_account_token_provider,
            )

            with open(opts["google_application_credentials"]) as fh:
                creds = json.load(fh)
            provider = service_account_token_provider(
                creds, scope="https://www.googleapis.com/auth/pubsub"
            )
        return GooglePubSubQueue(
            opts["project"], opts["topic"],
            token_provider=provider,
            endpoint=opts.get("endpoint", "https://pubsub.googleapis.com"),
        )
    raise ValueError(f"unknown notification kind {kind!r}")
