"""Cloud notification queues speaking the providers' REST protocols.

The reference wraps vendor SDKs (`weed/notification/aws_sqs/aws_sqs_pub.go`,
`google_pub_sub/google_pub_sub.go`); here the wire protocols are implemented
directly:

  - `SqsQueue`       — AWS SQS query protocol (GetQueueUrl + SendMessage)
    signed with SigV4 (service "sqs"), the `key` carried as a String
    message attribute and DelaySeconds=10, matching `aws_sqs_pub.go:74-95`.
  - `GooglePubSubQueue` — Pub/Sub REST `projects.topics.publish` with
    base64 payloads and the key as a message attribute, matching
    `google_pub_sub.go:60-88` (topic auto-create included).

Endpoints are overridable so contract tests drive the real client against
in-process fakes (`tests/test_cloud_sinks.py`).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
import urllib.parse

from seaweedfs_tpu.s3api.auth import (
    canonical_request,
    signing_key,
    string_to_sign,
)
from seaweedfs_tpu.server.httpd import http_request

from . import NotificationQueue


class SqsQueue(NotificationQueue):
    kind = "aws_sqs"

    def __init__(
        self,
        access_key: str,
        secret_key: str,
        region: str,
        queue_name: str,
        endpoint: str | None = None,
    ) -> None:
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.endpoint = (
            endpoint or f"https://sqs.{region}.amazonaws.com"
        ).rstrip("/")
        self.queue_url = self._get_queue_url(queue_name)

    def _signed_post(self, url: str, form: dict[str, str]) -> bytes:
        body = urllib.parse.urlencode(form).encode()
        parsed = urllib.parse.urlparse(url)
        now = time.gmtime()
        amz_date = time.strftime("%Y%m%dT%H%M%SZ", now)
        date = time.strftime("%Y%m%d", now)
        payload_hash = hashlib.sha256(body).hexdigest()
        headers = {
            "host": parsed.netloc,
            "x-amz-date": amz_date,
            "content-type": "application/x-www-form-urlencoded",
        }
        signed = sorted(headers)
        canon = canonical_request(
            "POST", parsed.path or "/", [], headers, signed, payload_hash
        )
        scope = f"{date}/{self.region}/sqs/aws4_request"
        sig = hmac.new(
            signing_key(self.secret_key, date, self.region, "sqs"),
            string_to_sign(amz_date, scope, canon).encode(),
            hashlib.sha256,
        ).hexdigest()
        headers["Authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
            f"SignedHeaders={';'.join(signed)}, Signature={sig}"
        )
        headers["x-amz-content-sha256"] = payload_hash
        status, _, resp = http_request("POST", url, body, headers)
        if status >= 400:
            raise IOError(f"sqs {form.get('Action')} -> {status}: {resp[:200]!r}")
        return resp

    def _get_queue_url(self, queue_name: str) -> str:
        resp = self._signed_post(
            self.endpoint + "/",
            {"Action": "GetQueueUrl", "QueueName": queue_name,
             "Version": "2012-11-05"},
        )
        # <GetQueueUrlResponse><GetQueueUrlResult><QueueUrl>...</QueueUrl>
        import xml.etree.ElementTree as ET

        root = ET.fromstring(resp)
        for el in root.iter():
            if el.tag.endswith("QueueUrl") and el.text:
                return el.text
        raise IOError(f"queue {queue_name} not found")

    def send_message(self, key: str, message: dict) -> None:
        self._signed_post(
            self.queue_url,
            {
                "Action": "SendMessage",
                "Version": "2012-11-05",
                "MessageBody": json.dumps(message),
                "DelaySeconds": "10",
                "MessageAttribute.1.Name": "key",
                "MessageAttribute.1.Value.DataType": "String",
                "MessageAttribute.1.Value.StringValue": key,
            },
        )


class GooglePubSubQueue(NotificationQueue):
    kind = "google_pub_sub"

    def __init__(
        self,
        project: str,
        topic: str,
        token_provider=None,
        endpoint: str = "https://pubsub.googleapis.com",
    ) -> None:
        self.project = project
        self.topic = topic
        if token_provider is None and "googleapis.com" in endpoint:
            raise ValueError(
                "google_pub_sub against the real endpoint needs credentials "
                "(google_application_credentials or token_provider)"
            )
        self.token = token_provider or (lambda: "")
        self.endpoint = endpoint.rstrip("/")
        self._ensure_topic()

    def _headers(self) -> dict[str, str]:
        h = {"Content-Type": "application/json"}
        tok = self.token()
        if tok:
            h["Authorization"] = f"Bearer {tok}"
        return h

    def _topic_path(self) -> str:
        return f"projects/{self.project}/topics/{self.topic}"

    def _ensure_topic(self) -> None:
        """google_pub_sub.go:45-55 creates the topic when it is absent.
        Anything other than found/absent (401/403/5xx) fails construction:
        a misconfigured queue must not pass startup and then drop events."""
        url = f"{self.endpoint}/v1/{self._topic_path()}"
        status, _, body = http_request("GET", url, None, self._headers())
        if status == 404:
            status, _, body = http_request("PUT", url, b"{}", self._headers())
            if status >= 400 and status != 409:
                raise IOError(f"pubsub create topic -> {status}: {body[:200]!r}")
        elif status >= 400:
            raise IOError(f"pubsub topic check -> {status}: {body[:200]!r}")

    def send_message(self, key: str, message: dict) -> None:
        payload = json.dumps(
            {
                "messages": [
                    {
                        "data": base64.b64encode(
                            json.dumps(message).encode()
                        ).decode(),
                        "attributes": {"key": key},
                    }
                ]
            }
        ).encode()
        url = f"{self.endpoint}/v1/{self._topic_path()}:publish"
        status, _, body = http_request("POST", url, payload, self._headers())
        if status >= 400:
            raise IOError(f"pubsub publish -> {status}: {body[:200]!r}")
