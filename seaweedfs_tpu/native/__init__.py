"""Native C++ CPU kernels loaded via ctypes.

The reference leaned on assembly inside Go libraries for its hot paths
(klauspost/reedsolomon AVX2 GF(2^8), stdlib SSE4.2 CRC32C, asm MD5 —
SURVEY.md §2.2). Here those CPU paths are C++ (`seaweedfs_tpu/native/src`),
compiled on first use into `_seaweed_native.so` and exposed through ctypes.
They serve as (a) the CPU fallback when no TPU is attached and (b) the
baseline the TPU kernels are benchmarked against.

If compilation fails (no toolchain), callers fall back to numpy paths —
correctness is preserved, only throughput drops.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src")
_SO_PATH = os.path.join(_HERE, "_seaweed_native.so")

_lock = threading.Lock()


class NativeLib:
    def __init__(self, cdll: ctypes.CDLL) -> None:
        self._lib = cdll
        self._lib.sw_crc32c_update.restype = ctypes.c_uint32
        self._lib.sw_crc32c_update.argtypes = [
            ctypes.c_uint32,
            ctypes.c_char_p,
            ctypes.c_size_t,
        ]
        self._lib.sw_gf256_matmul.restype = None
        self._lib.sw_gf256_matmul.argtypes = [
            ctypes.c_char_p,  # matrix rows*cols
            ctypes.c_int,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_char_p),  # input shard pointers [cols]
            ctypes.POINTER(ctypes.c_char_p),  # output shard pointers [rows]
            ctypes.c_size_t,  # shard length
        ]
        self._lib.sw_md5_batch.restype = None
        self._lib.sw_md5_batch.argtypes = [
            ctypes.c_void_p,  # blobs (accepts bytes or a numpy data pointer)
            ctypes.c_size_t,
            ctypes.c_size_t,
            ctypes.c_void_p,
        ]
        self._lib.sw_gear_boundaries.restype = ctypes.c_size_t
        self._lib.sw_gear_boundaries.argtypes = [
            ctypes.c_void_p,  # data
            ctypes.c_size_t,
            ctypes.c_void_p,  # gear table uint32[256]
            ctypes.c_uint32,  # mask
            ctypes.c_size_t,  # min_size
            ctypes.c_size_t,  # max_size
            ctypes.c_void_p,  # out cuts uint64[max_cuts]
            ctypes.c_size_t,
        ]
        self._lib.sw_crc32c_batch.restype = None
        self._lib.sw_crc32c_batch.argtypes = [
            ctypes.c_void_p,  # blobs (n * blob_len contiguous)
            ctypes.c_size_t,
            ctypes.c_size_t,
            ctypes.c_void_p,  # out uint32[n]
        ]
        self._lib.sw_md5_batch_var.restype = None
        self._lib.sw_md5_batch_var.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),  # blob pointers [n]
            ctypes.POINTER(ctypes.c_size_t),  # lengths [n]
            ctypes.c_size_t,
            ctypes.c_void_p,  # out (n, 16)
        ]
        self._lib.sw_crc32c_batch_var.restype = None
        self._lib.sw_crc32c_batch_var.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_size_t),
            ctypes.c_size_t,
            ctypes.c_void_p,  # out uint32[n]
        ]
        self._lib.sw_md5_batch_spans.restype = None
        self._lib.sw_md5_batch_spans.argtypes = [
            ctypes.c_void_p,  # base buffer
            ctypes.c_void_p,  # offs size_t[n]
            ctypes.c_void_p,  # lens size_t[n]
            ctypes.c_size_t,
            ctypes.c_void_p,  # out (n, 16)
        ]
        self._lib.sw_crc32c_batch_spans.restype = None
        self._lib.sw_crc32c_batch_spans.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_size_t,
            ctypes.c_void_p,  # out uint32[n]
        ]
        self._lib.sw_fast128.restype = None
        self._lib.sw_fast128.argtypes = [
            ctypes.c_void_p, ctypes.c_size_t, ctypes.c_char_p,
            ctypes.c_void_p,
        ]
        self._lib.sw_fast128_spans.restype = None
        self._lib.sw_fast128_spans.argtypes = [
            ctypes.c_void_p,  # base buffer
            ctypes.c_void_p,  # cuts size_t[n] (exclusive ends)
            ctypes.c_size_t,
            ctypes.c_char_p,  # 16-byte seed or None
            ctypes.c_void_p,  # out (n, 16)
        ]
        self._lib.sw_gf256_matmul2d.restype = None
        self._lib.sw_gf256_matmul2d.argtypes = [
            ctypes.c_char_p,  # matrix rows*cols
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_void_p,  # in (cols, n) row-major
            ctypes.c_void_p,  # out (rows, n) row-major
            ctypes.c_size_t,
        ]
        self._lib.sw_gf256_has_gfni.restype = ctypes.c_int
        self._lib.sw_gf256_has_gfni.argtypes = []
        self._lib.sw_gf256_set_gfni.restype = ctypes.c_int
        self._lib.sw_gf256_set_gfni.argtypes = [ctypes.c_int]
        self._lib.sw_ec_encode_volume.restype = ctypes.c_longlong
        self._lib.sw_ec_encode_volume.argtypes = [
            ctypes.c_char_p,  # matrix rows*cols
            ctypes.c_int,  # parity rows
            ctypes.c_int,  # data cols
            ctypes.c_int,  # dat fd
            ctypes.c_ulonglong,  # total .dat bytes
            ctypes.POINTER(ctypes.c_int),  # shard fds [cols+rows]
            ctypes.c_ulonglong,  # shard size
            ctypes.c_ulonglong,  # large block
            ctypes.c_ulonglong,  # small block
        ]
        self._lib.sw_gf256_matmul_fds.restype = ctypes.c_longlong
        self._lib.sw_gf256_matmul_fds.argtypes = [
            ctypes.c_char_p,  # matrix rows*cols
            ctypes.c_int,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int),  # input shard fds [cols]
            ctypes.c_ulonglong,  # bytes per shard
            ctypes.POINTER(ctypes.c_int),  # output shard fds [rows]
        ]
        self._lib.sw_gf256_encode_rows.restype = None
        self._lib.sw_gf256_encode_rows.argtypes = [
            ctypes.c_char_p,  # matrix rows*cols
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_void_p,  # in: row_count rows of cols*block bytes
            ctypes.c_size_t,  # block
            ctypes.c_int,  # row_count
            ctypes.c_void_p,  # out (rows, row_count*block)
        ]
        self._lib.sw_loadgen_assign_write.restype = ctypes.c_int
        self._lib.sw_loadgen_assign_write.argtypes = [
            ctypes.c_char_p,  # master host
            ctypes.c_int,  # master port
            ctypes.c_int,  # concurrent slots
            ctypes.c_size_t,  # files
            ctypes.c_char_p,  # assign path
            ctypes.c_char_p,  # body
            ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_ulonglong),
        ]
        self._lib.sw_loadgen.restype = ctypes.c_int
        self._lib.sw_loadgen.argtypes = [
            ctypes.c_char_p,  # host
            ctypes.c_int,  # port
            ctypes.c_int,  # concurrent keep-alive conns
            ctypes.c_char_p,  # method
            ctypes.c_char_p,  # \0-joined paths
            ctypes.c_size_t,  # path count
            ctypes.c_char_p,  # body (POST)
            ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_ulonglong),  # out: ok, err, ns
        ]

    def has(self, _name: str) -> bool:
        return True

    def crc32c_update(self, crc: int, data) -> int:
        if not isinstance(data, bytes):
            data = bytes(data)
        return int(self._lib.sw_crc32c_update(crc & 0xFFFFFFFF, data, len(data)))

    def gf256_matmul(self, matrix: bytes, rows: int, cols: int, inputs, out_len: int):
        """matrix is rows*cols GF(2^8) coefficients; inputs is a list of
        `cols` byte strings of length out_len; returns list of `rows` outputs."""
        in_arr = (ctypes.c_char_p * cols)(*[bytes(x) for x in inputs])
        outs = [ctypes.create_string_buffer(out_len) for _ in range(rows)]
        out_arr = (ctypes.c_char_p * rows)(
            *[ctypes.cast(o, ctypes.c_char_p) for o in outs]
        )
        self._lib.sw_gf256_matmul(matrix, rows, cols, in_arr, out_arr, out_len)
        return [o.raw for o in outs]

    def gf256_matmul2d(self, matrix: bytes, data, out=None):
        """Zero-copy variant: data is a C-contiguous uint8 numpy array
        (cols, n); writes/returns (rows, n). No per-shard byte copies —
        this is the pipeline hot path (ctypes releases the GIL)."""
        import numpy as np

        rows = len(matrix) // data.shape[0]
        cols, n = data.shape
        if out is None:
            out = np.empty((rows, n), dtype=np.uint8)
        self._lib.sw_gf256_matmul2d(
            matrix, rows, cols,
            data.ctypes.data, out.ctypes.data, n,
        )
        return out

    def gf256_encode_rows(self, matrix: bytes, parity: int, cols: int,
                          buf, block: int, row_count: int, out=None):
        """Row-batched encode (see sw_gf256_encode_rows). buf is a
        C-contiguous uint8 array of row_count*cols*block bytes; returns
        (parity, row_count*block) uint8."""
        import numpy as np

        if out is None:
            out = np.empty((parity, row_count * block), dtype=np.uint8)
        self._lib.sw_gf256_encode_rows(
            matrix, parity, cols, buf.ctypes.data, block, row_count,
            out.ctypes.data,
        )
        return out

    def ec_encode_volume(self, matrix: bytes, parity: int, cols: int,
                         dat_fd: int, total: int, shard_fds, shard_size: int,
                         large_block: int, small_block: int) -> int:
        """Whole-volume fused encode (see sw_ec_encode_volume): mmap'd .dat
        -> GFNI -> NT-stores into the (pre-truncated) mmap'd shard files.
        One GIL-released call; returns 0 on success, <0 => caller falls back
        to the staged pipeline."""
        fds = (ctypes.c_int * len(shard_fds))(*shard_fds)
        return int(self._lib.sw_ec_encode_volume(
            matrix, parity, cols, dat_fd, total, fds, shard_size,
            large_block, small_block,
        ))

    def gf256_matmul_fds(self, matrix: bytes, rows: int, cols: int,
                         in_fds, n: int, out_fds) -> int:
        """Fused matmul with fd-mmapped inputs/outputs (rebuild/decode hot
        path). Returns 0 on success, <0 => caller falls back."""
        ifds = (ctypes.c_int * cols)(*in_fds)
        ofds = (ctypes.c_int * rows)(*out_fds)
        return int(self._lib.sw_gf256_matmul_fds(matrix, rows, cols, ifds, n, ofds))

    def has_gfni(self) -> bool:
        return bool(self._lib.sw_gf256_has_gfni())

    def set_gfni(self, enabled: bool) -> bool:
        return bool(self._lib.sw_gf256_set_gfni(1 if enabled else 0))

    def md5_batch(self, blobs: bytes, n: int, blob_len: int) -> bytes:
        out = ctypes.create_string_buffer(n * 16)
        self._lib.sw_md5_batch(blobs, n, blob_len, ctypes.cast(out, ctypes.c_char_p))
        return out.raw

    def md5_batch_np(self, blobs, n: int, blob_len: int):
        """Zero-copy batch MD5: blobs is a C-contiguous uint8 numpy array
        (n, blob_len); returns (n, 16) uint8."""
        import numpy as np

        out = np.empty((n, 16), dtype=np.uint8)
        self._lib.sw_md5_batch(blobs.ctypes.data, n, blob_len, out.ctypes.data)
        return out

    def md5_crc_batch_var(self, blobs: list) -> tuple:
        """Variable-length batch MD5+CRC32C: blobs is a list of bytes
        objects (zero-copy pointers). Hash the batch LENGTH-SORTED for full
        lane utilization, returning results in the caller's order.
        Returns ((n, 16) uint8 digests, (n,) uint32 crcs)."""
        import numpy as np

        n = len(blobs)
        order = sorted(range(n), key=lambda i: -len(blobs[i]))
        ptrs = (ctypes.c_char_p * n)(*[blobs[i] for i in order])
        lens = (ctypes.c_size_t * n)(*[len(blobs[i]) for i in order])
        dig_s = np.empty((n, 16), dtype=np.uint8)
        crc_s = np.empty(n, dtype=np.uint32)
        self._lib.sw_md5_batch_var(ptrs, lens, n, dig_s.ctypes.data)
        self._lib.sw_crc32c_batch_var(ptrs, lens, n, crc_s.ctypes.data)
        digests = np.empty_like(dig_s)
        crcs = np.empty_like(crc_s)
        digests[order] = dig_s
        crcs[order] = crc_s
        return digests, crcs

    def md5_crc_batch_spans(self, buf, cuts) -> tuple:
        """Zero-copy span hashing: buf is one contiguous uint8 buffer (numpy
        array or bytes), cuts the CDC exclusive chunk ends. No per-chunk
        Python slices — the C side length-sorts and runs the lockstep
        kernels. Returns ((n, 16) uint8 digests, (n,) uint32 crcs)."""
        import numpy as np

        arr = np.frombuffer(buf, dtype=np.uint8) if not isinstance(
            buf, np.ndarray
        ) else buf
        ends = np.asarray(cuts, dtype=np.uintp)
        offs = np.empty_like(ends)
        offs[0] = 0
        offs[1:] = ends[:-1]
        lens = ends - offs
        n = len(ends)
        digests = np.empty((n, 16), dtype=np.uint8)
        crcs = np.empty(n, dtype=np.uint32)
        self._lib.sw_md5_batch_spans(
            arr.ctypes.data, offs.ctypes.data, lens.ctypes.data, n,
            digests.ctypes.data,
        )
        self._lib.sw_crc32c_batch_spans(
            arr.ctypes.data, offs.ctypes.data, lens.ctypes.data, n,
            crcs.ctypes.data,
        )
        return digests, crcs

    def md5_spans(self, buf, offs, lens):
        """MD5 of arbitrary (offset, length) spans of one buffer — the
        dedup path hashes ONLY the chunks that missed the index (their
        upload ETags); identity keys come from fast128_spans."""
        import numpy as np

        arr = np.frombuffer(buf, dtype=np.uint8) if not isinstance(
            buf, np.ndarray
        ) else buf
        o = np.asarray(offs, dtype=np.uintp)
        l = np.asarray(lens, dtype=np.uintp)
        n = len(o)
        digests = np.empty((n, 16), dtype=np.uint8)
        self._lib.sw_md5_batch_spans(
            arr.ctypes.data, o.ctypes.data, l.ctypes.data, n,
            digests.ctypes.data,
        )
        return digests

    def fast128(self, data: bytes, seed: bytes = b"") -> bytes:
        """SW128 of one buffer (16 bytes) — the dedup identity hash.
        seed: per-store 16-byte secret (defends against offline collision
        construction); empty = the unseeded golden form."""
        import numpy as np

        arr = np.frombuffer(data, dtype=np.uint8) if not isinstance(
            data, np.ndarray) else data
        out = np.empty(16, dtype=np.uint8)
        self._lib.sw_fast128(arr.ctypes.data, arr.nbytes, seed or None,
                             out.ctypes.data)
        return out.tobytes()

    def fast128_spans(self, buf, cuts, seed: bytes = b""):
        """SW128 per CDC span of one contiguous buffer (cuts = exclusive
        ends). Returns (n, 16) uint8 — the dedup index identity keys,
        ~2.5x cheaper than the MD5 span batch (ops/hash_service.span_keys)."""
        import numpy as np

        arr = np.frombuffer(buf, dtype=np.uint8) if not isinstance(
            buf, np.ndarray
        ) else buf
        ends = np.asarray(cuts, dtype=np.uintp)
        n = len(ends)
        out = np.empty((n, 16), dtype=np.uint8)
        self._lib.sw_fast128_spans(
            arr.ctypes.data, ends.ctypes.data, n, seed or None,
            out.ctypes.data,
        )
        return out

    def gear_boundaries(self, data, gear, mask: int, min_size: int,
                        max_size: int):
        """Serial gear-CDC cut positions. data: uint8 numpy array; gear:
        uint32[256] numpy. Returns a uint64 numpy array of exclusive ends."""
        import numpy as np

        max_cuts = max(16, len(data) // max(min_size, 1) + 2)
        cuts = np.empty(max_cuts, dtype=np.uint64)
        n = self._lib.sw_gear_boundaries(
            data.ctypes.data, len(data), gear.ctypes.data, mask,
            min_size, max_size, cuts.ctypes.data, max_cuts,
        )
        return cuts[:n]

    def loadgen(self, host: str, port: int, conns: int, method: str,
                paths: list, body: bytes | None = None) -> dict:
        """Drive an HTTP server with keep-alive connections from native code
        (one epoll thread, no GIL in the request loop). Returns ok/err
        counts and req/s — the measuring stick for the fastlane engine."""
        blob = b"".join(
            (p if isinstance(p, bytes) else p.encode()) + b"\0" for p in paths
        )
        out = (ctypes.c_ulonglong * 3)()
        rc = self._lib.sw_loadgen(
            host.encode(), port, conns, method.encode(), blob, len(paths),
            body, len(body) if body else 0, out,
        )
        secs = out[2] / 1e9 if out[2] else 1.0
        result = {
            "ok": int(out[0]),
            "errors": int(out[1]),  # C side accounts every unfinished path
            "seconds": round(secs, 3),
            "req_per_sec": round(out[0] / secs, 1),
        }
        if rc != 0:
            result["error"] = f"sw_loadgen rc={rc} (connect failure)"
        return result

    def loadgen_assign_write(self, host: str, master_port: int, conns: int,
                             files: int, body: bytes,
                             assign_path: str = "/dir/assign") -> dict:
        """Per-file assign -> write load (`weed benchmark` write semantics:
        every file pays a master round-trip for its fid, then a volume
        POST)."""
        out = (ctypes.c_ulonglong * 3)()
        rc = self._lib.sw_loadgen_assign_write(
            host.encode(), master_port, conns, files, assign_path.encode(),
            body, len(body), out,
        )
        secs = out[2] / 1e9 if out[2] else 1.0
        result = {
            "ok": int(out[0]),
            "errors": int(out[1]),
            "seconds": round(secs, 3),
            "req_per_sec": round(out[0] / secs, 1),
        }
        if rc != 0:
            result["error"] = f"rc={rc} (connect failure)"
        return result

    def crc32c_batch(self, blobs, n: int, blob_len: int):
        """blobs: C-contiguous uint8 numpy array (n, blob_len) — zero-copy;
        returns (n,) uint32."""
        import numpy as np

        out = np.empty(n, dtype=np.uint32)
        self._lib.sw_crc32c_batch(
            blobs.ctypes.data, n, blob_len, out.ctypes.data
        )
        return out


def _build() -> bool:
    srcs = [os.path.join(_SRC, f) for f in sorted(os.listdir(_SRC)) if f.endswith(".cpp")]
    cmd = [
        "g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
        "-o", _SO_PATH, *srcs,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        try:  # retry without -march=native for odd toolchains
            cmd.remove("-march=native")
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            return True
        except Exception:
            return False


def _load() -> NativeLib | None:
    with _lock:
        if not os.path.exists(_SO_PATH) or any(
            os.path.getmtime(os.path.join(_SRC, f)) > os.path.getmtime(_SO_PATH)
            for f in os.listdir(_SRC)
            if f.endswith(".cpp")
        ):
            if not _build():
                return None
        try:
            return NativeLib(ctypes.CDLL(_SO_PATH))
        except OSError:
            return None


lib: NativeLib | None = None
if os.environ.get("SEAWEEDFS_TPU_DISABLE_NATIVE") != "1":
    try:
        lib = _load()
    except Exception:
        lib = None
