// Standalone sanitizer harness for the fastlane engine — the native-code
// arm of the suite's race-detection strategy (tests/test_fastlane_tsan.py
// builds this with -fsanitize=thread / address and runs it).
//
// It stands up a real engine (plus a trivial in-process backend server),
// registers a volume on scratch files, then hammers it from concurrent
// client threads with interleaved native writes/reads/deletes, proxied
// requests, Python-side-style lock/tail/map calls, drains, and
// register/unregister churn — the exact cross-thread surfaces the Python
// suite exercises through servers, minus Python.
//
// Build: g++ -std=c++17 -fsanitize=thread -DSW_FASTLANE_SANITY_MAIN \
//        fastlane_sanity.cpp fastlane.cpp crc32c.cpp sha256.cpp ... -o t
#ifdef SW_FASTLANE_SANITY_MAIN

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <string>
#include <thread>
#include <vector>

extern "C" {
int sw_fl_start(const char* host, int port, const char* backend_host,
                int backend_port, int workers, int secure_reads,
                int secure_writes, int max_backend,
                const char* jwt_write_key, const char* jwt_read_key,
                const char* tls_cert, const char* tls_key,
                const char* tls_ca, const char* tls_allowed_cns);
int sw_fl_port(int h);
void sw_fl_stop(int h);
int sw_fl_register_volume(int h, uint32_t vid, int dat_fd, int idx_fd,
                          int version, unsigned long long tail,
                          unsigned long long last_append_ns, int readonly,
                          int forward_writes);
int sw_fl_volume_serving(int h, uint32_t vid);
int sw_fl_unregister_volume(int h, uint32_t vid);
int sw_fl_set_flags(int h, uint32_t vid, int readonly, int forward_writes);
int sw_fl_volume_lock(int h, uint32_t vid);
int sw_fl_volume_unlock(int h, uint32_t vid);
unsigned long long sw_fl_tail_get(int h, uint32_t vid);
int sw_fl_tail_set(int h, uint32_t vid, unsigned long long tail,
                   unsigned long long last_ns);
int sw_fl_map_put(int h, uint32_t vid, uint64_t key,
                  unsigned long long offset, int32_t size);
long sw_fl_drain_events(int h, uint8_t* out, size_t max_events);
void sw_fl_get_stats(int h, unsigned long long* out6);
long sw_fl_get_metrics(int h, unsigned long long* out, size_t cap);
int sw_fl_get_volume_metrics(int h, uint32_t vid, unsigned long long* out6);
int sw_fl_filer_enable(int h, const char* journal_path,
                       unsigned long long chunk_limit, int compress);
int sw_fl_filer_lease_set(int h, const char* vol_host, int vol_port,
                          uint32_t vid, uint32_t cookie,
                          unsigned long long key_start,
                          unsigned long long key_end, const char* upload_auth,
                          const char* read_auth);
unsigned long long sw_fl_filer_lease_remaining(int h);
int sw_fl_filer_cache_put(int h, const char* path, const char* host,
                          int port, const char* fid, const char* mime,
                          const char* md5_hex, unsigned long long size,
                          unsigned long long mtime, const void* inline_data,
                          size_t inline_len);
int sw_fl_filer_cache_del(int h, const char* path);
long sw_fl_filer_drain(int h, uint8_t* out, size_t cap);
long sw_fl_filer_journal_reset(int h);
}

namespace {

// minimal backend: accepts, answers every request with a tiny 200
void backend_loop(int listen_fd, std::atomic<bool>* running) {
    while (running->load()) {
        int fd = accept(listen_fd, nullptr, nullptr);
        if (fd < 0) continue;
        std::thread([fd, running] {
            char buf[8192];
            std::string in;
            while (running->load()) {
                ssize_t n = recv(fd, buf, sizeof buf, 0);
                if (n <= 0) break;
                in.append(buf, n);
                size_t he;
                while ((he = in.find("\r\n\r\n")) != std::string::npos) {
                    size_t cl = 0;
                    const char* f = strcasestr(in.c_str(), "content-length:");
                    if (f && f < in.c_str() + he)
                        cl = strtoull(f + 15, nullptr, 10);
                    if (in.size() < he + 4 + cl) break;
                    in.erase(0, he + 4 + cl);
                    const char* resp =
                        "HTTP/1.1 200 OK\r\nContent-Length: 2\r\n\r\nok";
                    if (send(fd, resp, strlen(resp), MSG_NOSIGNAL) <= 0)
                        break;
                }
            }
            close(fd);
        }).detach();
    }
}

int tcp_listen(int* port_out) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    struct sockaddr_in sa;
    memset(&sa, 0, sizeof sa);
    sa.sin_family = AF_INET;
    sa.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    bind(fd, (struct sockaddr*)&sa, sizeof sa);
    listen(fd, 64);
    socklen_t sl = sizeof sa;
    getsockname(fd, (struct sockaddr*)&sa, &sl);
    *port_out = ntohs(sa.sin_port);
    return fd;
}

int dial(int port) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    struct sockaddr_in sa;
    memset(&sa, 0, sizeof sa);
    sa.sin_family = AF_INET;
    sa.sin_port = htons(port);
    sa.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    if (connect(fd, (struct sockaddr*)&sa, sizeof sa) != 0) {
        close(fd);
        return -1;
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    return fd;
}

// one keep-alive request; returns the status code or -1
int do_req(int fd, const std::string& req) {
    if (send(fd, req.data(), req.size(), MSG_NOSIGNAL) <= 0) return -1;
    std::string resp;
    char buf[8192];
    for (;;) {
        ssize_t n = recv(fd, buf, sizeof buf, 0);
        if (n <= 0) return -1;
        resp.append(buf, n);
        size_t he = resp.find("\r\n\r\n");
        if (he == std::string::npos) continue;
        size_t cl = 0;
        const char* f = strcasestr(resp.c_str(), "content-length:");
        if (f && f < resp.c_str() + he) cl = strtoull(f + 15, nullptr, 10);
        if (resp.size() >= he + 4 + cl)
            return atoi(resp.c_str() + 9);
    }
}

}  // namespace

// process-lifetime flag: detached backend threads may outlive main()'s
// frame, so this must not live on main's stack
static std::atomic<bool> g_running{true};

int main() {
    std::atomic<bool>& running = g_running;
    int backend_port = 0;
    int backend_fd = tcp_listen(&backend_port);
    std::thread bt(backend_loop, backend_fd, &running);

    int h = sw_fl_start("127.0.0.1", 0, "127.0.0.1", backend_port, 4, 0, 0,
                        8, "", "", "", "", "", "");
    if (h < 0) { fprintf(stderr, "engine start failed\n"); return 1; }
    int port = sw_fl_port(h);

    char dat_path[] = "/tmp/fl_sanity_dat_XXXXXX";
    char idx_path[] = "/tmp/fl_sanity_idx_XXXXXX";
    int dat_fd = mkstemp(dat_path);
    int idx_fd = mkstemp(idx_path);
    // superblock filler so offsets are nonzero like a real volume
    uint8_t super[8] = {0};
    (void)!write(dat_fd, super, 8);
    fcntl(idx_fd, F_SETFL, O_APPEND);
    sw_fl_register_volume(h, 7, dup(dat_fd), dup(idx_fd), 3, 8, 0, 0, 0);
    sw_fl_volume_serving(h, 7);

    const int THREADS = 6, OPS = 400;
    std::atomic<uint64_t> next_key{1};
    std::atomic<int> errors{0};
    std::vector<std::thread> ts;
    for (int t = 0; t < THREADS; t++) {
        ts.emplace_back([&, t] {
            int fd = dial(port);
            if (fd < 0) { errors++; return; }
            char req[512];
            for (int i = 0; i < OPS; i++) {
                uint64_t key = next_key.fetch_add(1);
                int n = snprintf(req, sizeof req,
                                 "POST /7,%llxdeadbeef HTTP/1.1\r\nHost: x\r\n"
                                 "Content-Length: 64\r\n\r\n",
                                 (unsigned long long)key);
                std::string r(req, n);
                r.append(64, (char)('a' + t));
                int st = do_req(fd, r);
                if (st != 201 && st != 200) { errors++; break; }
                n = snprintf(req, sizeof req,
                             "GET /7,%llxdeadbeef HTTP/1.1\r\nHost: x\r\n\r\n",
                             (unsigned long long)key);
                st = do_req(fd, std::string(req, n));
                if (st != 200) { errors++; break; }
                if (i % 7 == 0) {  // proxied path
                    st = do_req(fd, "GET /status HTTP/1.1\r\nHost: x\r\n\r\n");
                    if (st != 200) { errors++; break; }
                }
                if (i % 5 == 0) {
                    n = snprintf(req, sizeof req,
                                 "DELETE /7,%llxdeadbeef HTTP/1.1\r\n"
                                 "Host: x\r\n\r\n",
                                 (unsigned long long)key);
                    if (do_req(fd, std::string(req, n)) != 202) {
                        errors++;
                        break;
                    }
                }
            }
            close(fd);
        });
    }
    // Python-side style interleaving: drains, flag flips, lock/tail hooks
    std::thread admin([&] {
        // 48 = sizeof(Event) in fastlane.cpp (grew from 40 when trace_id
        // was added): a 40B/event buffer overflows whenever >= 214 events
        // back up between drains — which the hammering workers on a slow
        // box absolutely produce (ASan caught exactly that)
        uint8_t evbuf[48 * 256];
        for (int i = 0; i < 300; i++) {
            sw_fl_drain_events(h, evbuf, 256);
            sw_fl_set_flags(h, 7, 0, 0);
            sw_fl_volume_lock(h, 7);
            unsigned long long tail = sw_fl_tail_get(h, 7);
            sw_fl_tail_set(h, 7, tail, 0);
            sw_fl_volume_unlock(h, 7);
            // put + delete churn: both sides of the map_mu surface
            sw_fl_map_put(h, 7, 1000000 + i, 4096 + 8 * i, 128);
            sw_fl_map_put(h, 7, 1000000 + i, 0, 0);
            // concurrent metrics scrapes against the hammering workers
            // (the PR-2 per-op histograms are relaxed atomics; any
            // accidental non-atomic path shows up here under TSAN)
            unsigned long long mbuf[256], vm[6];
            sw_fl_get_metrics(h, mbuf, 256);
            sw_fl_get_volume_metrics(h, 7, vm);
            usleep(1000);
        }
    });
    for (auto& th : ts) th.join();
    admin.join();

    unsigned long long stats[6];
    sw_fl_get_stats(h, stats);
    fprintf(stderr,
            "requests=%llu native_writes=%llu native_reads=%llu "
            "deletes=%llu proxied=%llu errors=%d\n",
            stats[0], stats[2], stats[1], stats[3], stats[4], errors.load());
    {
        // the metrics snapshot must agree with the plain counters
        unsigned long long m[256];
        long written = sw_fl_get_metrics(h, m, 256);
        if (written < 2) { fprintf(stderr, "get_metrics failed\n"); return 1; }
        size_t nb = (size_t)m[1];
        unsigned long long reads = m[2 + nb];      // op 0 count
        unsigned long long writes = m[2 + nb + (3 + nb + 1)];
        if (reads != stats[1] || writes != stats[2]) {
            fprintf(stderr, "metrics/stats mismatch r=%llu/%llu w=%llu/%llu\n",
                    reads, stats[1], writes, stats[2]);
            return 1;
        }
    }

    // ---- filer-mode phase: a SECOND engine acts as the filer, leasing
    // fids against the first (volume) engine — inline writes (journal +
    // cache under filer_mu/fcache_mu), chunk uploads (engine->engine
    // BackendConn pools), reads (inline serve + relay), against
    // concurrent drains, cache churn, and re-leases
    int fh = sw_fl_start("127.0.0.1", 0, "127.0.0.1", backend_port, 4, 0, 0,
                         8, "", "", "", "", "", "");
    if (fh < 0) { fprintf(stderr, "filer engine start failed\n"); return 1; }
    char jpath[] = "/tmp/fl_sanity_journal_XXXXXX";
    int jfd = mkstemp(jpath);
    close(jfd);
    sw_fl_filer_enable(fh, jpath, 4u << 20, 0);
    sw_fl_filer_lease_set(fh, "127.0.0.1", port, 7, 0xcafe1234u,
                          1u << 20, (1u << 20) + 100000, "", "");
    int fport = sw_fl_port(fh);
    std::atomic<int> ferrors{0};
    std::vector<std::thread> fts;
    for (int t = 0; t < THREADS; t++) {
        fts.emplace_back([&, t] {
            int fd = dial(fport);
            if (fd < 0) { ferrors++; return; }
            char req[512];
            for (int i = 0; i < OPS / 2; i++) {
                bool inline_write = (i % 2) == 0;
                size_t body = inline_write ? 512 : 8192;
                int n = snprintf(req, sizeof req,
                                 "POST /s/t%d-f%d HTTP/1.1\r\nHost: x\r\n"
                                 "Content-Length: %zu\r\n\r\n",
                                 t, i, body);
                std::string r(req, n);
                r.append(body, (char)('a' + t));
                int st = do_req(fd, r);
                if (st != 201) { ferrors++; break; }
                n = snprintf(req, sizeof req,
                             "GET /s/t%d-f%d HTTP/1.1\r\nHost: x\r\n\r\n",
                             t, i);
                st = do_req(fd, std::string(req, n));
                // chunk reads may miss the cache into the proxied 200
                if (st != 200) { ferrors++; break; }
            }
            close(fd);
        });
    }
    std::thread fadmin([&] {
        uint8_t fbuf[1 << 16];
        char path[64];
        for (int i = 0; i < 200; i++) {
            sw_fl_filer_drain(fh, fbuf, sizeof fbuf);
            sw_fl_filer_journal_reset(fh);
            snprintf(path, sizeof path, "/adm/x%d", i);
            sw_fl_filer_cache_put(fh, path, "127.0.0.1", port, "7,1deadbeef",
                                  "", "0123456789abcdef0123456789abcdef",
                                  64, 1234, "inlinebytes", 11);
            if (i % 3 == 0) sw_fl_filer_cache_del(fh, path);
            if (i % 50 == 0)  // re-lease churn (flease_mu writers)
                sw_fl_filer_lease_set(fh, "127.0.0.1", port, 7, 0xcafe1234u,
                                      (2u << 20) + i * 1000,
                                      (2u << 20) + i * 1000 + 100000, "", "");
            sw_fl_filer_lease_remaining(fh);
            usleep(1000);
        }
    });
    for (auto& th : fts) th.join();
    fadmin.join();
    unsigned long long fstats[6];
    sw_fl_get_stats(fh, fstats);
    fprintf(stderr,
            "filer: requests=%llu native_writes=%llu native_reads=%llu "
            "proxied=%llu errors=%d\n",
            fstats[0], fstats[2], fstats[1], fstats[4], ferrors.load());
    sw_fl_stop(fh);
    unlink(jpath);
    if (ferrors.load() != 0) { fprintf(stderr, "filer phase errors\n"); return 1; }

    // register/unregister churn against live traffic already stopped;
    // exercise the lifecycle surface once more
    unsigned long long final_tail = sw_fl_tail_get(h, 7);
    sw_fl_unregister_volume(h, 7);
    sw_fl_register_volume(h, 7, dup(dat_fd), dup(idx_fd), 3,
                          final_tail, 0, 0, 0);
    sw_fl_volume_serving(h, 7);
    sw_fl_unregister_volume(h, 7);

    sw_fl_stop(h);
    running.store(false);
    shutdown(backend_fd, SHUT_RDWR);
    close(backend_fd);
    bt.join();
    close(dat_fd);
    close(idx_fd);
    unlink(dat_path);
    unlink(idx_path);

    if (errors.load() != 0) return 2;
    if (stats[2] < (unsigned long long)(THREADS * OPS * 0.9)) return 3;
    fprintf(stderr, "fastlane sanity OK\n");
    return 0;
}

#endif  // SW_FASTLANE_SANITY_MAIN
