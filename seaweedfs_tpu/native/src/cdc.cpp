// Gear-CDC boundary scan. Bit-identical to the data-parallel XOR-window
// hash in ops/cdc.py: h_i = XOR_{k<32} G[b_{i-k}] << k, whose serial
// recurrence is h = (h << 1) ^ G[b] (the k=32 term self-shifts out of
// uint32). The window rolls straight across cut points, exactly like the
// vectorized path which hashes every position of the buffer first and picks
// cuts afterwards. Cut rule per ops/cdc.py find_boundaries: first position
// i >= start+min_size with (h_i & mask) == 0 cuts at i+1; otherwise cut at
// start+max_size (or n).
//
// Two speed tricks, both exact:
// 1. h_i depends on only the last 32 bytes (G entries are uint32, so
//    contributions shifted >= 32 bits vanish) — after a cut the scan jumps
//    to start+min_size-32 and re-warms the window with 32 bytes, skipping
//    the table walk over the rest of the minimum chunk.
// 2. The serial recurrence's 2-cycle/byte dependency chain is broken with
//    AVX-512: 16 positions advance per step via a log-step lane-prefix XOR
//    (P_j = XOR_{m<=j} v_m << (j-m)), candidates found with one compare
//    mask — boundaries are ~2^-avg_bits dense, so the common path is
//    branch-free. Verified bit-identical to the scalar loop at init.
#include <cstdint>
#include <cstddef>
#include <cstring>
#include <initializer_list>

#if defined(__AVX512F__) && defined(__AVX512BW__)
#include <immintrin.h>
#define SW_CDC_AVX512 1
#endif

namespace {

// scalar reference core: advance h over [i, end) testing for candidates
// with position >= can_from; returns end or the cut position's byte index.
inline size_t scan_scalar(const unsigned char* data, size_t i, size_t end,
                          size_t can_from, const uint32_t* gear,
                          uint32_t mask, uint32_t& h, bool& found) {
    for (; i < end; i++) {
        h = (h << 1) ^ gear[data[i]];
        if (i >= can_from && (h & mask) == 0) {
            found = true;
            return i;
        }
    }
    found = false;
    return end;
}

#ifdef SW_CDC_AVX512
// vector core: same contract as scan_scalar, 16 bytes per iteration.
size_t scan_vec(const unsigned char* data, size_t i, size_t end,
                size_t can_from, const uint32_t* gear, uint32_t mask,
                uint32_t& h, bool& found) {
    const __m512i lane_idx =
        _mm512_setr_epi32(0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15);
    const __m512i shift_amt = _mm512_setr_epi32(1, 2, 3, 4, 5, 6, 7, 8, 9, 10,
                                                11, 12, 13, 14, 15, 16);
    const __m512i vmask = _mm512_set1_epi32((int)mask);
    const __m512i zero = _mm512_setzero_si512();
    // permute indices for lane-left-shift by 1/2/4/8 (lane j takes j-s)
    const __m512i p1 = _mm512_setr_epi32(0, 0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10,
                                         11, 12, 13, 14);
    const __m512i p2 = _mm512_setr_epi32(0, 0, 0, 1, 2, 3, 4, 5, 6, 7, 8, 9,
                                         10, 11, 12, 13);
    const __m512i p4 = _mm512_setr_epi32(0, 0, 0, 0, 0, 1, 2, 3, 4, 5, 6, 7,
                                         8, 9, 10, 11);
    const __m512i p8 = _mm512_setr_epi32(0, 0, 0, 0, 0, 0, 0, 0, 0, 1, 2, 3,
                                         4, 5, 6, 7);
    // two independent 16-lane groups per iteration: the two gathers (the
    // long-latency op) overlap, and the second group's prefix combine only
    // serializes at the final h-carry xor
    auto prefix = [&](__m512i v) {
        __m512i p = v;
        p = _mm512_xor_si512(
            p, _mm512_slli_epi32(
                   _mm512_maskz_permutexvar_epi32(0xFFFE, p1, p), 1));
        p = _mm512_xor_si512(
            p, _mm512_slli_epi32(
                   _mm512_maskz_permutexvar_epi32(0xFFFC, p2, p), 2));
        p = _mm512_xor_si512(
            p, _mm512_slli_epi32(
                   _mm512_maskz_permutexvar_epi32(0xFFF0, p4, p), 4));
        p = _mm512_xor_si512(
            p, _mm512_slli_epi32(
                   _mm512_maskz_permutexvar_epi32(0xFF00, p8, p), 8));
        return p;
    };
    auto lane_filter = [&](size_t base_i) -> __mmask16 {
        if (can_from <= base_i) return 0xFFFF;
        return (__mmask16)(can_from - base_i >= 16
                               ? 0
                               : (0xFFFF << (can_from - base_i)));
    };
    while (i + 32 <= end) {
        __m128i b0 = _mm_loadu_si128((const __m128i*)(data + i));
        __m128i b1 = _mm_loadu_si128((const __m128i*)(data + i + 16));
        __m512i v0 = _mm512_i32gather_epi32(
            _mm512_cvtepu8_epi32(b0), (const int*)gear, 4);
        __m512i v1 = _mm512_i32gather_epi32(
            _mm512_cvtepu8_epi32(b1), (const int*)gear, 4);
        __m512i pA = prefix(v0);
        __m512i pB = prefix(v1);
        __m512i hv = _mm512_sllv_epi32(_mm512_set1_epi32((int)h), shift_amt);
        __m512i H0 = _mm512_xor_si512(pA, hv);
        alignas(64) uint32_t hs0[16], hs1[16];
        _mm512_store_si512(hs0, H0);
        uint32_t h_mid = hs0[15];
        __m512i hv1 = _mm512_sllv_epi32(
            _mm512_set1_epi32((int)h_mid), shift_amt);
        __m512i H1 = _mm512_xor_si512(pB, hv1);
        __mmask16 cand0 = _mm512_cmpeq_epi32_mask(
            _mm512_and_si512(H0, vmask), zero) & lane_filter(i);
        if (cand0) {
            int lane = __builtin_ctz((unsigned)cand0);
            h = hs0[lane];
            found = true;
            return i + lane;
        }
        __mmask16 cand1 = _mm512_cmpeq_epi32_mask(
            _mm512_and_si512(H1, vmask), zero) & lane_filter(i + 16);
        if (cand1) {
            int lane = __builtin_ctz((unsigned)cand1);
            _mm512_store_si512(hs1, H1);
            h = hs1[lane];
            found = true;
            return i + 16 + lane;
        }
        _mm512_store_si512(hs1, H1);
        h = hs1[15];
        i += 32;
    }
    while (i + 16 <= end) {
        __m128i bytes = _mm_loadu_si128((const __m128i*)(data + i));
        __m512i idx = _mm512_cvtepu8_epi32(bytes);
        __m512i v = _mm512_i32gather_epi32(idx, (const int*)gear, 4);
        __m512i p = prefix(v);
        // H_j = P_j ^ (h << (j+1))  (lanes j+1 > 31 impossible: max 16)
        __m512i hv = _mm512_sllv_epi32(_mm512_set1_epi32((int)h), shift_amt);
        __m512i H = _mm512_xor_si512(p, hv);
        __mmask16 cand = _mm512_cmpeq_epi32_mask(
            _mm512_and_si512(H, vmask), zero) & lane_filter(i);
        alignas(64) uint32_t hs[16];
        _mm512_store_si512(hs, H);
        if (cand) {
            int lane = __builtin_ctz((unsigned)cand);
            h = hs[lane];
            found = true;
            return i + lane;
        }
        h = hs[15];
        i += 16;
    }
    return scan_scalar(data, i, end, can_from, gear, mask, h, found);
}

bool cdc_selftest() {
    // random-ish data, tiny mask so candidates are dense; compare cores
    unsigned char buf[4096];
    uint32_t gear[256];
    uint32_t s = 2463534242u;
    for (int i = 0; i < 4096; i++) {
        s ^= s << 13; s ^= s >> 17; s ^= s << 5;
        buf[i] = (unsigned char)s;
    }
    for (int i = 0; i < 256; i++) {
        s ^= s << 13; s ^= s >> 17; s ^= s << 5;
        gear[i] = s;
    }
    for (uint32_t mask : {0xFFu, 0x3Fu, 0x1FFFu}) {
        size_t i1 = 7, i2 = 7;
        uint32_t h1 = 12345, h2 = 12345;
        while (true) {
            bool f1 = false, f2 = false;
            i1 = scan_scalar(buf, i1, 4096, 19, gear, mask, h1, f1);
            i2 = scan_vec(buf, i2, 4096, 19, gear, mask, h2, f2);
            if (i1 != i2 || h1 != h2 || f1 != f2) return false;
            if (!f1) break;
            i1++; i2++;
        }
    }
    return true;
}
#endif

} // namespace

extern "C" size_t sw_gear_boundaries(const unsigned char* data, size_t n,
                                     const uint32_t* gear, uint32_t mask,
                                     size_t min_size, size_t max_size,
                                     uint64_t* cuts, size_t max_cuts) {
#ifdef SW_CDC_AVX512
    // magic static: thread-safe lazy selftest (concurrent first uploads)
    static const bool cdc_avx512_usable =
        __builtin_cpu_supports("avx512f") &&
        __builtin_cpu_supports("avx512bw") && cdc_selftest();
#endif
    size_t count = 0;
    size_t start = 0;
    size_t i = 0;
    uint32_t h = 0;
    while (i < n) {
        // window trick: h at any position needs only the previous 32 bytes,
        // so jump to 32 bytes before the first cut-eligible position
        size_t can_from = start + min_size;  // first index where a cut may land
        if (can_from >= 32 && i < can_from - 32) {
            i = can_from - 32;
            h = 0;
        }
        size_t span_end = start + max_size - 1;  // forced-cut byte index
        if (span_end > n - 1) span_end = n - 1;
        bool found = false;
#ifdef SW_CDC_AVX512
        size_t at = cdc_avx512_usable
                        ? scan_vec(data, i, span_end + 1, can_from, gear, mask,
                                   h, found)
                        : scan_scalar(data, i, span_end + 1, can_from, gear,
                                      mask, h, found);
#else
        size_t at = scan_scalar(data, i, span_end + 1, can_from, gear, mask,
                                h, found);
#endif
        if (found) {
            if (count == max_cuts) return count;
            cuts[count++] = at + 1;
            start = at + 1;
            i = at + 1;
        } else if (span_end == start + max_size - 1) {
            if (count == max_cuts) return count;
            cuts[count++] = span_end + 1;  // max_size forced cut
            start = span_end + 1;
            i = span_end + 1;
        } else {
            break;  // ran off the end of the buffer
        }
    }
    if (start < n && count < max_cuts) cuts[count++] = n;
    return count;
}
