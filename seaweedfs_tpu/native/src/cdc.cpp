// Serial gear-CDC boundary scan. Bit-identical to the data-parallel
// XOR-window hash in ops/cdc.py: h_i = XOR_{k<32} G[b_{i-k}] << k, whose
// serial recurrence is h = (h << 1) ^ G[b] (the k=32 term self-shifts out
// of uint32). The window rolls straight across cut points, exactly like the
// vectorized path which hashes every position of the buffer first and picks
// cuts afterwards. Cut rule per ops/cdc.py find_boundaries: first position
// i >= start+min_size with (h_i & mask) == 0 cuts at i+1; otherwise cut at
// start+max_size (or n). ~1 GB/s single core; the TPU kernel is the batch
// path.
#include <cstdint>
#include <cstddef>

extern "C" size_t sw_gear_boundaries(const unsigned char* data, size_t n,
                                     const uint32_t* gear, uint32_t mask,
                                     size_t min_size, size_t max_size,
                                     uint64_t* cuts, size_t max_cuts) {
    size_t count = 0;
    size_t start = 0;
    uint32_t h = 0;
    for (size_t i = 0; i < n; i++) {
        h = (h << 1) ^ gear[data[i]];
        bool cut = false;
        if (i >= start + min_size && (h & mask) == 0)
            cut = true;
        else if (i + 1 - start == max_size)
            cut = true;
        if (cut) {
            if (count == max_cuts) return count;
            cuts[count++] = i + 1;
            start = i + 1;
        }
    }
    if (start < n && count < max_cuts) cuts[count++] = n;
    return count;
}
