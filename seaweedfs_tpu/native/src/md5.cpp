// Batched MD5 over equal-length blobs. CPU equivalent of the multi-buffer
// MD5 technique (Intel isa-l / minio's md5-simd) standing in for Go's asm
// crypto/md5 on the reference's upload path
// (weed/server/filer_server_handlers_write_upload.go:48): MD5 is strictly
// sequential per stream, so the win is width — 16 independent blobs advance
// in lockstep, one per 32-bit AVX-512 lane, message words fetched with
// vpgatherdd. Scalar fallback kept for tails / non-AVX512 builds, verified
// identical at init.
#include <cstdint>
#include <cstddef>
#include <cstring>
#include <algorithm>
#include <vector>

#if defined(__AVX512F__)
#include <immintrin.h>
#define SW_MD5_AVX512 1
#endif

namespace {

struct MD5Ctx {
    uint32_t a, b, c, d;
};

const uint32_t K[64] = {
    0xd76aa478, 0xe8c7b756, 0x242070db, 0xc1bdceee, 0xf57c0faf, 0x4787c62a,
    0xa8304613, 0xfd469501, 0x698098d8, 0x8b44f7af, 0xffff5bb1, 0x895cd7be,
    0x6b901122, 0xfd987193, 0xa679438e, 0x49b40821, 0xf61e2562, 0xc040b340,
    0x265e5a51, 0xe9b6c7aa, 0xd62f105d, 0x02441453, 0xd8a1e681, 0xe7d3fbc8,
    0x21e1cde6, 0xc33707d6, 0xf4d50d87, 0x455a14ed, 0xa9e3e905, 0xfcefa3f8,
    0x676f02d9, 0x8d2a4c8a, 0xfffa3942, 0x8771f681, 0x6d9d6122, 0xfde5380c,
    0xa4beea44, 0x4bdecfa9, 0xf6bb4b60, 0xbebfbc70, 0x289b7ec6, 0xeaa127fa,
    0xd4ef3085, 0x04881d05, 0xd9d4d039, 0xe6db99e5, 0x1fa27cf8, 0xc4ac5665,
    0xf4292244, 0x432aff97, 0xab9423a7, 0xfc93a039, 0x655b59c3, 0x8f0ccc92,
    0xffeff47d, 0x85845dd1, 0x6fa87e4f, 0xfe2ce6e0, 0xa3014314, 0x4e0811a1,
    0xf7537e82, 0xbd3af235, 0x2ad7d2bb, 0xeb86d391};

const int S[64] = {7, 12, 17, 22, 7, 12, 17, 22, 7, 12, 17, 22, 7, 12, 17, 22,
                   5, 9,  14, 20, 5, 9,  14, 20, 5, 9,  14, 20, 5, 9,  14, 20,
                   4, 11, 16, 23, 4, 11, 16, 23, 4, 11, 16, 23, 4, 11, 16, 23,
                   6, 10, 15, 21, 6, 10, 15, 21, 6, 10, 15, 21, 6, 10, 15, 21};

inline uint32_t rotl(uint32_t x, int s) { return (x << s) | (x >> (32 - s)); }

void md5_block(MD5Ctx& ctx, const uint8_t* p) {
    uint32_t m[16];
    std::memcpy(m, p, 64);
    uint32_t a = ctx.a, b = ctx.b, c = ctx.c, d = ctx.d;
    for (int i = 0; i < 64; i++) {
        uint32_t f;
        int g;
        if (i < 16) { f = (b & c) | (~b & d); g = i; }
        else if (i < 32) { f = (d & b) | (~d & c); g = (5 * i + 1) & 15; }
        else if (i < 48) { f = b ^ c ^ d; g = (3 * i + 5) & 15; }
        else { f = c ^ (b | ~d); g = (7 * i) & 15; }
        uint32_t tmp = d;
        d = c;
        c = b;
        b = b + rotl(a + f + K[i] + m[g], S[i]);
        a = tmp;
    }
    ctx.a += a; ctx.b += b; ctx.c += c; ctx.d += d;
}

void md5_one(const uint8_t* data, size_t len, uint8_t* out) {
    MD5Ctx ctx{0x67452301, 0xefcdab89, 0x98badcfe, 0x10325476};
    size_t full = len / 64;
    for (size_t i = 0; i < full; i++) md5_block(ctx, data + i * 64);
    uint8_t tail[128] = {0};
    size_t rem = len - full * 64;
    std::memcpy(tail, data + full * 64, rem);
    tail[rem] = 0x80;
    size_t tail_len = (rem + 9 <= 64) ? 64 : 128;
    uint64_t bits = (uint64_t)len * 8;
    std::memcpy(tail + tail_len - 8, &bits, 8);
    md5_block(ctx, tail);
    if (tail_len == 128) md5_block(ctx, tail + 64);
    std::memcpy(out, &ctx.a, 4);
    std::memcpy(out + 4, &ctx.b, 4);
    std::memcpy(out + 8, &ctx.c, 4);
    std::memcpy(out + 12, &ctx.d, 4);
}

#ifdef SW_MD5_AVX512
// 16 blobs in lockstep: state vectors hold lane l = blob l's (a,b,c,d).
// Message word g of block `blk` for lane l sits at l*blob_len + blk*64 + g*4
// — one vpgatherdd per round fetches it for all 16 lanes.
inline __m512i rotl16(__m512i x, int s) {
    return _mm512_or_si512(_mm512_slli_epi32(x, s), _mm512_srli_epi32(x, 32 - s));
}

void md5_16lane(const uint8_t* base, size_t blob_len, uint8_t* out) {
    const __m512i lane_off = _mm512_mullo_epi32(
        _mm512_setr_epi32(0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15),
        _mm512_set1_epi32((int)blob_len));
    __m512i a = _mm512_set1_epi32((int)0x67452301);
    __m512i b = _mm512_set1_epi32((int)0xefcdab89);
    __m512i c = _mm512_set1_epi32((int)0x98badcfe);
    __m512i d = _mm512_set1_epi32((int)0x10325476);
    const __m512i ones = _mm512_set1_epi32(-1);
    size_t full = blob_len / 64;
    for (size_t blk = 0; blk < full; blk++) {
        __m512i m[16];
        const uint8_t* p = base + blk * 64;
        for (int g = 0; g < 16; g++)
            m[g] = _mm512_i32gather_epi32(lane_off, (const int*)(p + g * 4), 1);
        __m512i aa = a, bb = b, cc = c, dd = d;
        for (int i = 0; i < 64; i++) {
            __m512i f;
            int g;
            if (i < 16) {
                f = _mm512_or_si512(_mm512_and_si512(bb, cc),
                                    _mm512_andnot_si512(bb, dd));
                g = i;
            } else if (i < 32) {
                f = _mm512_or_si512(_mm512_and_si512(dd, bb),
                                    _mm512_andnot_si512(dd, cc));
                g = (5 * i + 1) & 15;
            } else if (i < 48) {
                f = _mm512_xor_si512(_mm512_xor_si512(bb, cc), dd);
                g = (3 * i + 5) & 15;
            } else {
                f = _mm512_xor_si512(cc,
                                     _mm512_or_si512(bb, _mm512_xor_si512(dd, ones)));
                g = (7 * i) & 15;
            }
            __m512i sum = _mm512_add_epi32(
                _mm512_add_epi32(aa, f),
                _mm512_add_epi32(_mm512_set1_epi32((int)K[i]), m[g]));
            __m512i tmp = dd;
            dd = cc;
            cc = bb;
            bb = _mm512_add_epi32(bb, rotl16(sum, S[i]));
            aa = tmp;
        }
        a = _mm512_add_epi32(a, aa);
        b = _mm512_add_epi32(b, bb);
        c = _mm512_add_epi32(c, cc);
        d = _mm512_add_epi32(d, dd);
    }
    uint8_t tail[128];
    size_t rem = blob_len - full * 64;
    size_t tail_len = (rem + 9 <= 64) ? 64 : 128;
    uint32_t av[16], bv[16], cv[16], dv[16];
    _mm512_storeu_si512(av, a);
    _mm512_storeu_si512(bv, b);
    _mm512_storeu_si512(cv, c);
    _mm512_storeu_si512(dv, d);
    // finish tails (remainder + padding) per lane with the scalar core:
    // cheap — at most 2 blocks of the whole blob
    for (int l = 0; l < 16; l++) {
        MD5Ctx ctx{av[l], bv[l], cv[l], dv[l]};
        const uint8_t* data = base + (size_t)l * blob_len;
        std::memcpy(tail, data + full * 64, rem);
        std::memset(tail + rem, 0, sizeof(tail) - rem);
        tail[rem] = 0x80;
        uint64_t bits = (uint64_t)blob_len * 8;
        std::memcpy(tail + tail_len - 8, &bits, 8);
        md5_block(ctx, tail);
        if (tail_len == 128) md5_block(ctx, tail + 64);
        uint8_t* o = out + (size_t)l * 16;
        std::memcpy(o, &ctx.a, 4);
        std::memcpy(o + 4, &ctx.b, 4);
        std::memcpy(o + 8, &ctx.c, 4);
        std::memcpy(o + 12, &ctx.d, 4);
    }
}

// Variable-length lockstep: 16 blobs of DIFFERENT lengths advance together,
// each lane staging its own next 64B block into a contiguous 16x64 buffer
// (L1-resident, so the per-round vpgatherdd hits cache); lanes whose blob
// ran out of full blocks retire via merge-masked state adds. Callers get
// the most out of it by length-sorting the batch so groups retire together
// (CDC dedup chunks have content-defined, i.e. unique, lengths — the
// equal-length kernel above degenerates to scalar there).
void md5_16lane_var(const uint8_t* const ptrs[16], const size_t lens[16],
                    uint8_t* out) {
    alignas(64) uint8_t stage[16 * 64];
    const __m512i lane_off = _mm512_slli_epi32(
        _mm512_setr_epi32(0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15),
        6);  // l*64: lane l's block lives at stage + l*64
    __m512i a = _mm512_set1_epi32((int)0x67452301);
    __m512i b = _mm512_set1_epi32((int)0xefcdab89);
    __m512i c = _mm512_set1_epi32((int)0x98badcfe);
    __m512i d = _mm512_set1_epi32((int)0x10325476);
    const __m512i ones = _mm512_set1_epi32(-1);
    size_t full[16];
    size_t maxfull = 0;
    for (int l = 0; l < 16; l++) {
        full[l] = lens[l] / 64;
        if (full[l] > maxfull) maxfull = full[l];
    }
    for (size_t blk = 0; blk < maxfull; blk++) {
        __mmask16 active = 0;
        for (int l = 0; l < 16; l++)
            if (blk < full[l]) {
                std::memcpy(stage + l * 64, ptrs[l] + blk * 64, 64);
                active |= (__mmask16)(1u << l);
            }
        __m512i m[16];
        for (int g = 0; g < 16; g++)
            m[g] = _mm512_i32gather_epi32(lane_off, (const int*)(stage + g * 4), 1);
        __m512i aa = a, bb = b, cc = c, dd = d;
        for (int i = 0; i < 64; i++) {
            __m512i f;
            int g;
            if (i < 16) {
                f = _mm512_or_si512(_mm512_and_si512(bb, cc),
                                    _mm512_andnot_si512(bb, dd));
                g = i;
            } else if (i < 32) {
                f = _mm512_or_si512(_mm512_and_si512(dd, bb),
                                    _mm512_andnot_si512(dd, cc));
                g = (5 * i + 1) & 15;
            } else if (i < 48) {
                f = _mm512_xor_si512(_mm512_xor_si512(bb, cc), dd);
                g = (3 * i + 5) & 15;
            } else {
                f = _mm512_xor_si512(cc,
                                     _mm512_or_si512(bb, _mm512_xor_si512(dd, ones)));
                g = (7 * i) & 15;
            }
            __m512i sum = _mm512_add_epi32(
                _mm512_add_epi32(aa, f),
                _mm512_add_epi32(_mm512_set1_epi32((int)K[i]), m[g]));
            __m512i tmp = dd;
            dd = cc;
            cc = bb;
            bb = _mm512_add_epi32(bb, rotl16(sum, S[i]));
            aa = tmp;
        }
        a = _mm512_mask_add_epi32(a, active, a, aa);
        b = _mm512_mask_add_epi32(b, active, b, bb);
        c = _mm512_mask_add_epi32(c, active, c, cc);
        d = _mm512_mask_add_epi32(d, active, d, dd);
    }
    uint32_t av[16], bv[16], cv[16], dv[16];
    _mm512_storeu_si512(av, a);
    _mm512_storeu_si512(bv, b);
    _mm512_storeu_si512(cv, c);
    _mm512_storeu_si512(dv, d);
    uint8_t tail[128];
    for (int l = 0; l < 16; l++) {
        MD5Ctx ctx{av[l], bv[l], cv[l], dv[l]};
        size_t rem = lens[l] - full[l] * 64;
        size_t tail_len = (rem + 9 <= 64) ? 64 : 128;
        std::memset(tail, 0, sizeof(tail));
        std::memcpy(tail, ptrs[l] + full[l] * 64, rem);
        tail[rem] = 0x80;
        uint64_t bits = (uint64_t)lens[l] * 8;
        std::memcpy(tail + tail_len - 8, &bits, 8);
        md5_block(ctx, tail);
        if (tail_len == 128) md5_block(ctx, tail + 64);
        uint8_t* o = out + (size_t)l * 16;
        std::memcpy(o, &ctx.a, 4);
        std::memcpy(o + 4, &ctx.b, 4);
        std::memcpy(o + 8, &ctx.c, 4);
        std::memcpy(o + 12, &ctx.d, 4);
    }
}

bool md5_avx512_ok() {
    static int ok = -1;
    if (ok >= 0) return ok;
    if (!__builtin_cpu_supports("avx512f")) { ok = 0; return false; }
    // self-test 16 lanes vs scalar
    uint8_t blobs[16 * 128], want[16 * 16], got[16 * 16];
    for (int i = 0; i < 16 * 128; i++) blobs[i] = (uint8_t)(i * 31 + 7);
    for (int l = 0; l < 16; l++) md5_one(blobs + l * 128, 128, want + l * 16);
    md5_16lane(blobs, 128, got);
    ok = std::memcmp(want, got, sizeof(want)) == 0;
    return ok;
}
#endif

} // namespace

extern "C" void sw_md5_batch(const unsigned char* blobs, size_t n,
                             size_t blob_len, unsigned char* out) {
    size_t i = 0;
#ifdef SW_MD5_AVX512
    if (blob_len >= 64 && n >= 16 && md5_avx512_ok()) {
        for (; i + 16 <= n; i += 16)
            md5_16lane(blobs + i * blob_len, blob_len, out + i * 16);
    }
#endif
    for (; i < n; i++)
        md5_one(blobs + i * blob_len, blob_len, out + i * 16);
}

// Variable-length batch: ptrs/lens describe n independent blobs anywhere in
// memory. Caller should length-sort for best lane utilization; groups of 16
// run the lockstep kernel, the remainder runs scalar.
extern "C" void sw_md5_batch_var(const unsigned char* const* ptrs,
                                 const size_t* lens, size_t n,
                                 unsigned char* out) {
    size_t i = 0;
#ifdef SW_MD5_AVX512
    if (n >= 16 && md5_avx512_ok()) {
        for (; i + 16 <= n; i += 16)
            md5_16lane_var(ptrs + i, lens + i, out + i * 16);
    }
#endif
    for (; i < n; i++) md5_one(ptrs[i], lens[i], out + i * 16);
}

// Span batch: n sub-ranges of one contiguous buffer (CDC chunks of an
// upload) — zero per-piece copies on the Python side. Length-sorts
// internally so lockstep lanes retire together, restoring caller order.
extern "C" void sw_md5_batch_spans(const unsigned char* base,
                                   const size_t* offs, const size_t* lens,
                                   size_t n, unsigned char* out) {
    if (n == 0) return;
    std::vector<size_t> order(n);
    for (size_t i = 0; i < n; i++) order[i] = i;
    std::sort(order.begin(), order.end(),
              [&](size_t a, size_t b) { return lens[a] > lens[b]; });
    std::vector<const unsigned char*> ptrs(n);
    std::vector<size_t> slens(n);
    for (size_t i = 0; i < n; i++) {
        ptrs[i] = base + offs[order[i]];
        slens[i] = lens[order[i]];
    }
    std::vector<unsigned char> tmp(n * 16);
    sw_md5_batch_var(ptrs.data(), slens.data(), n, tmp.data());
    for (size_t i = 0; i < n; i++)
        std::memcpy(out + order[i] * 16, tmp.data() + i * 16, 16);
}
