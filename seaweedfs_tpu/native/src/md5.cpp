// Batched MD5 over equal-length blobs. CPU stand-in for Go's asm crypto/md5
// used on the reference's upload path
// (weed/server/filer_server_handlers_write_upload.go:48).
#include <cstdint>
#include <cstddef>
#include <cstring>

namespace {

struct MD5Ctx {
    uint32_t a, b, c, d;
};

const uint32_t K[64] = {
    0xd76aa478, 0xe8c7b756, 0x242070db, 0xc1bdceee, 0xf57c0faf, 0x4787c62a,
    0xa8304613, 0xfd469501, 0x698098d8, 0x8b44f7af, 0xffff5bb1, 0x895cd7be,
    0x6b901122, 0xfd987193, 0xa679438e, 0x49b40821, 0xf61e2562, 0xc040b340,
    0x265e5a51, 0xe9b6c7aa, 0xd62f105d, 0x02441453, 0xd8a1e681, 0xe7d3fbc8,
    0x21e1cde6, 0xc33707d6, 0xf4d50d87, 0x455a14ed, 0xa9e3e905, 0xfcefa3f8,
    0x676f02d9, 0x8d2a4c8a, 0xfffa3942, 0x8771f681, 0x6d9d6122, 0xfde5380c,
    0xa4beea44, 0x4bdecfa9, 0xf6bb4b60, 0xbebfbc70, 0x289b7ec6, 0xeaa127fa,
    0xd4ef3085, 0x04881d05, 0xd9d4d039, 0xe6db99e5, 0x1fa27cf8, 0xc4ac5665,
    0xf4292244, 0x432aff97, 0xab9423a7, 0xfc93a039, 0x655b59c3, 0x8f0ccc92,
    0xffeff47d, 0x85845dd1, 0x6fa87e4f, 0xfe2ce6e0, 0xa3014314, 0x4e0811a1,
    0xf7537e82, 0xbd3af235, 0x2ad7d2bb, 0xeb86d391};

const int S[64] = {7, 12, 17, 22, 7, 12, 17, 22, 7, 12, 17, 22, 7, 12, 17, 22,
                   5, 9,  14, 20, 5, 9,  14, 20, 5, 9,  14, 20, 5, 9,  14, 20,
                   4, 11, 16, 23, 4, 11, 16, 23, 4, 11, 16, 23, 4, 11, 16, 23,
                   6, 10, 15, 21, 6, 10, 15, 21, 6, 10, 15, 21, 6, 10, 15, 21};

inline uint32_t rotl(uint32_t x, int s) { return (x << s) | (x >> (32 - s)); }

void md5_block(MD5Ctx& ctx, const uint8_t* p) {
    uint32_t m[16];
    std::memcpy(m, p, 64);
    uint32_t a = ctx.a, b = ctx.b, c = ctx.c, d = ctx.d;
    for (int i = 0; i < 64; i++) {
        uint32_t f;
        int g;
        if (i < 16) { f = (b & c) | (~b & d); g = i; }
        else if (i < 32) { f = (d & b) | (~d & c); g = (5 * i + 1) & 15; }
        else if (i < 48) { f = b ^ c ^ d; g = (3 * i + 5) & 15; }
        else { f = c ^ (b | ~d); g = (7 * i) & 15; }
        uint32_t tmp = d;
        d = c;
        c = b;
        b = b + rotl(a + f + K[i] + m[g], S[i]);
        a = tmp;
    }
    ctx.a += a; ctx.b += b; ctx.c += c; ctx.d += d;
}

void md5_one(const uint8_t* data, size_t len, uint8_t* out) {
    MD5Ctx ctx{0x67452301, 0xefcdab89, 0x98badcfe, 0x10325476};
    size_t full = len / 64;
    for (size_t i = 0; i < full; i++) md5_block(ctx, data + i * 64);
    uint8_t tail[128] = {0};
    size_t rem = len - full * 64;
    std::memcpy(tail, data + full * 64, rem);
    tail[rem] = 0x80;
    size_t tail_len = (rem + 9 <= 64) ? 64 : 128;
    uint64_t bits = (uint64_t)len * 8;
    std::memcpy(tail + tail_len - 8, &bits, 8);
    md5_block(ctx, tail);
    if (tail_len == 128) md5_block(ctx, tail + 64);
    std::memcpy(out, &ctx.a, 4);
    std::memcpy(out + 4, &ctx.b, 4);
    std::memcpy(out + 8, &ctx.c, 4);
    std::memcpy(out + 12, &ctx.d, 4);
}

} // namespace

extern "C" void sw_md5_batch(const unsigned char* blobs, size_t n,
                             size_t blob_len, unsigned char* out) {
    for (size_t i = 0; i < n; i++)
        md5_one(blobs + i * blob_len, blob_len, out + i * 16);
}
