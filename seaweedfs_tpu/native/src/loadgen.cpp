// Minimal epoll HTTP load generator — measures the fastlane engine's
// ceiling without a GIL-bound client in the way (bench.py small-file
// configs). One thread, N keep-alive connections, one in-flight request
// per connection; counts 2xx and completes when every path ran once.

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#include <string>
#include <vector>

namespace {

struct LgConn {
    int fd = -1;
    std::string out;
    size_t out_off = 0;
    std::string in;
    size_t expect = 0;   // response bytes needed (0 = headers not parsed)
    int path_idx = -1;
};

uint64_t lg_now_ns() {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (uint64_t)ts.tv_sec * 1000000000ull + ts.tv_nsec;
}

int lg_connect(uint32_t ip, int port) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    struct sockaddr_in sa;
    memset(&sa, 0, sizeof sa);
    sa.sin_family = AF_INET;
    sa.sin_port = htons(port);
    sa.sin_addr.s_addr = ip;
    if (connect(fd, (struct sockaddr*)&sa, sizeof sa) != 0) {
        close(fd);
        return -1;
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    int fl = fcntl(fd, F_GETFL, 0);
    fcntl(fd, F_SETFL, fl | O_NONBLOCK);
    return fd;
}

}  // namespace

extern "C" {

// paths: npaths zero-terminated strings, back to back. method "GET",
// "POST", "PUT" or "DELETE". body sent with every POST/PUT when non-null.
// out[0]=ok count, out[1]=error count, out[2]=elapsed ns.
int sw_loadgen(const char* host, int port, int n_conns, const char* method,
               const char* paths, size_t npaths, const char* body,
               size_t body_len, unsigned long long* out3) {
    uint32_t ip = inet_addr(host && *host ? host : "127.0.0.1");
    std::vector<const char*> pv;
    pv.reserve(npaths);
    const char* p = paths;
    for (size_t i = 0; i < npaths; i++) {
        pv.push_back(p);
        p += strlen(p) + 1;
    }
    bool is_post =
        strcmp(method, "POST") == 0 || strcmp(method, "PUT") == 0;
    size_t next_path = 0, done = 0, ok = 0, errs = 0;
    int ep = epoll_create1(0);
    std::vector<LgConn> conns(n_conns);

    auto arm = [&](LgConn& c) -> bool {
        if (next_path >= pv.size()) return false;
        c.path_idx = (int)next_path++;
        char hdr[512];
        int n;
        if (is_post)
            n = snprintf(hdr, sizeof hdr,
                         "%s %s HTTP/1.1\r\nHost: lg\r\nContent-Length: %zu\r\n\r\n",
                         method, pv[c.path_idx], body_len);
        else
            n = snprintf(hdr, sizeof hdr, "%s %s HTTP/1.1\r\nHost: lg\r\n\r\n",
                         method, pv[c.path_idx]);
        c.out.assign(hdr, n);
        if (is_post && body_len) c.out.append(body, body_len);
        c.out_off = 0;
        c.in.clear();
        c.expect = 0;
        return true;
    };

    uint64_t t0 = lg_now_ns();
    for (int i = 0; i < n_conns && (size_t)i < pv.size(); i++) {
        conns[i].fd = lg_connect(ip, port);
        if (conns[i].fd < 0) { out3[0] = 0; out3[1] = npaths; out3[2] = 0; close(ep); return -1; }
        arm(conns[i]);
        struct epoll_event ev;
        ev.events = EPOLLIN | EPOLLOUT;
        ev.data.u32 = i;
        epoll_ctl(ep, EPOLL_CTL_ADD, conns[i].fd, &ev);
    }

    struct epoll_event evs[128];
    while (done < pv.size()) {
        int n = epoll_wait(ep, evs, 128, 10000);
        if (n <= 0) break;  // stall: bail out rather than hang the bench
        for (int i = 0; i < n; i++) {
            LgConn& c = conns[evs[i].data.u32];
            if (c.fd < 0) continue;
            bool fail = (evs[i].events & (EPOLLERR | EPOLLHUP)) != 0;
            if (!fail && (evs[i].events & EPOLLOUT)) {
                while (c.out_off < c.out.size()) {
                    ssize_t w = send(c.fd, c.out.data() + c.out_off,
                                     c.out.size() - c.out_off, MSG_NOSIGNAL);
                    if (w > 0) { c.out_off += w; continue; }
                    if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) break;
                    fail = true;
                    break;
                }
                if (!fail && c.out_off >= c.out.size()) {
                    struct epoll_event ev;
                    ev.events = EPOLLIN;
                    ev.data.u32 = evs[i].data.u32;
                    epoll_ctl(ep, EPOLL_CTL_MOD, c.fd, &ev);
                }
            }
            if (!fail && (evs[i].events & EPOLLIN)) {
                char buf[65536];
                for (;;) {
                    ssize_t r = recv(c.fd, buf, sizeof buf, 0);
                    if (r > 0) { c.in.append(buf, r); continue; }
                    if (r == 0) { fail = true; }
                    else if (errno != EAGAIN && errno != EWOULDBLOCK) fail = true;
                    break;
                }
                if (!fail && c.expect == 0) {
                    size_t he = c.in.find("\r\n\r\n");
                    if (he != std::string::npos) {
                        size_t cl = 0;
                        const char* f = strcasestr(c.in.c_str(), "content-length:");
                        if (f && f < c.in.c_str() + he) cl = strtoull(f + 15, nullptr, 10);
                        c.expect = he + 4 + cl;
                    }
                }
                if (!fail && c.expect && c.in.size() >= c.expect) {
                    if (c.in.compare(0, 10, "HTTP/1.1 2") == 0) ok++;
                    else errs++;
                    done++;
                    if (arm(c)) {
                        struct epoll_event ev;
                        ev.events = EPOLLIN | EPOLLOUT;
                        ev.data.u32 = evs[i].data.u32;
                        epoll_ctl(ep, EPOLL_CTL_MOD, c.fd, &ev);
                    } else {
                        epoll_ctl(ep, EPOLL_CTL_DEL, c.fd, nullptr);
                        close(c.fd);
                        c.fd = -1;
                    }
                }
            }
            if (fail) {
                errs++;
                done++;  // count the in-flight request as failed
                epoll_ctl(ep, EPOLL_CTL_DEL, c.fd, nullptr);
                close(c.fd);
                c.fd = lg_connect(ip, port);  // reconnect and continue
                if (c.fd >= 0 && arm(c)) {
                    struct epoll_event ev;
                    ev.events = EPOLLIN | EPOLLOUT;
                    ev.data.u32 = evs[i].data.u32;
                    epoll_ctl(ep, EPOLL_CTL_ADD, c.fd, &ev);
                } else if (c.fd >= 0) {
                    close(c.fd);
                    c.fd = -1;
                }
            }
        }
    }
    uint64_t t1 = lg_now_ns();
    for (auto& c : conns)
        if (c.fd >= 0) close(c.fd);
    close(ep);
    out3[0] = ok;
    out3[1] = errs + (pv.size() - done);
    out3[2] = t1 - t0;
    return 0;
}

// Per-file assign -> write flow (`weed benchmark` semantics): every file
// costs one GET /dir/assign on the master and one POST of the body to the
// returned volume location. n_conns independent two-socket slots.
int sw_loadgen_assign_write(const char* host, int master_port, int n_conns,
                            size_t n_files, const char* assign_path,
                            const char* body, size_t body_len,
                            unsigned long long* out3) {
    struct Slot {
        LgConn m;  // master leg
        LgConn v;  // volume leg
        int phase = 0;      // 0 assigning, 1 writing
        std::string vaddr;  // host:port the volume conn points at
    };
    uint32_t mip = inet_addr(host && *host ? host : "127.0.0.1");
    size_t launched = 0, done = 0, ok = 0, errs = 0;
    int ep = epoll_create1(0);
    std::vector<Slot> slots(n_conns);

    char assign_req[256];
    int assign_len = snprintf(assign_req, sizeof assign_req,
                              "GET %s HTTP/1.1\r\nHost: lg\r\n\r\n",
                              assign_path && *assign_path ? assign_path
                                                          : "/dir/assign");

    auto mod = [&](int fd, uint32_t data, uint32_t events) {
        struct epoll_event ev;
        ev.events = events;
        ev.data.u32 = data;
        epoll_ctl(ep, EPOLL_CTL_MOD, fd, &ev);
    };

    auto start_assign = [&](size_t si) -> bool {
        if (launched >= n_files) return false;
        launched++;
        Slot& s = slots[si];
        s.phase = 0;
        s.m.out.assign(assign_req, assign_len);
        s.m.out_off = 0;
        s.m.in.clear();
        s.m.expect = 0;
        mod(s.m.fd, (uint32_t)(si * 2), EPOLLIN | EPOLLOUT);
        return true;
    };

    uint64_t t0 = lg_now_ns();
    for (int i = 0; i < n_conns && (size_t)i < n_files; i++) {
        slots[i].m.fd = lg_connect(mip, master_port);
        if (slots[i].m.fd < 0) {
            out3[0] = 0; out3[1] = n_files; out3[2] = 0;
            close(ep);
            return -1;
        }
        struct epoll_event ev;
        ev.events = 0;
        ev.data.u32 = (uint32_t)(i * 2);
        epoll_ctl(ep, EPOLL_CTL_ADD, slots[i].m.fd, &ev);
        start_assign(i);
    }

    auto fail_slot = [&](size_t si) {
        // count the in-flight file as failed and move on with fresh conns
        Slot& s = slots[si];
        errs++;
        done++;
        if (s.m.fd >= 0) { epoll_ctl(ep, EPOLL_CTL_DEL, s.m.fd, nullptr); close(s.m.fd); }
        if (s.v.fd >= 0) { epoll_ctl(ep, EPOLL_CTL_DEL, s.v.fd, nullptr); close(s.v.fd); s.v.fd = -1; s.vaddr.clear(); }
        s.m.fd = lg_connect(mip, master_port);
        if (s.m.fd >= 0) {
            struct epoll_event ev;
            ev.events = 0;
            ev.data.u32 = (uint32_t)(si * 2);
            epoll_ctl(ep, EPOLL_CTL_ADD, s.m.fd, &ev);
            start_assign(si);
        }
    };

    struct epoll_event evs[128];
    while (done < n_files) {
        int n = epoll_wait(ep, evs, 128, 10000);
        if (n <= 0) break;
        for (int i = 0; i < n; i++) {
            size_t si = evs[i].data.u32 / 2;
            bool is_vol = evs[i].data.u32 & 1;
            Slot& s = slots[si];
            LgConn& c = is_vol ? s.v : s.m;
            if (c.fd < 0) continue;
            bool fail = (evs[i].events & (EPOLLERR | EPOLLHUP)) != 0;
            if (!fail && (evs[i].events & EPOLLOUT)) {
                while (c.out_off < c.out.size()) {
                    ssize_t w = send(c.fd, c.out.data() + c.out_off,
                                     c.out.size() - c.out_off, MSG_NOSIGNAL);
                    if (w > 0) { c.out_off += w; continue; }
                    if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) break;
                    fail = true;
                    break;
                }
                if (!fail && c.out_off >= c.out.size())
                    mod(c.fd, evs[i].data.u32, EPOLLIN);
            }
            if (!fail && (evs[i].events & EPOLLIN)) {
                char buf[65536];
                for (;;) {
                    ssize_t r = recv(c.fd, buf, sizeof buf, 0);
                    if (r > 0) { c.in.append(buf, r); continue; }
                    if (r == 0) fail = true;
                    else if (errno != EAGAIN && errno != EWOULDBLOCK) fail = true;
                    break;
                }
                if (!fail && c.expect == 0) {
                    size_t he = c.in.find("\r\n\r\n");
                    if (he != std::string::npos) {
                        size_t cl = 0;
                        const char* f = strcasestr(c.in.c_str(), "content-length:");
                        if (f && f < c.in.c_str() + he)
                            cl = strtoull(f + 15, nullptr, 10);
                        c.expect = he + 4 + cl;
                    }
                }
                if (!fail && c.expect && c.in.size() >= c.expect) {
                    bool ok2xx = c.in.compare(0, 10, "HTTP/1.1 2") == 0;
                    if (s.phase == 0) {
                        // parse {"fid": "...", ..., "publicUrl": "..."}
                        std::string fid, purl;
                        const char* fp = strstr(c.in.c_str(), "\"fid\": \"");
                        if (fp) {
                            fp += 8;
                            const char* e = strchr(fp, '"');
                            if (e) fid.assign(fp, e - fp);
                        }
                        const char* pp = strstr(c.in.c_str(), "\"publicUrl\": \"");
                        if (pp) {
                            pp += 14;
                            const char* e = strchr(pp, '"');
                            if (e) purl.assign(pp, e - pp);
                        }
                        if (!ok2xx || fid.empty() || purl.empty()) {
                            fail_slot(si);
                            continue;
                        }
                        if (s.v.fd < 0 || s.vaddr != purl) {
                            if (s.v.fd >= 0) {
                                epoll_ctl(ep, EPOLL_CTL_DEL, s.v.fd, nullptr);
                                close(s.v.fd);
                            }
                            size_t colon = purl.rfind(':');
                            std::string vh = purl.substr(0, colon);
                            int vp = atoi(purl.c_str() + colon + 1);
                            s.v.fd = lg_connect(inet_addr(vh.c_str()), vp);
                            if (s.v.fd < 0) { fail_slot(si); continue; }
                            s.vaddr = purl;
                            struct epoll_event ev;
                            ev.events = 0;
                            ev.data.u32 = (uint32_t)(si * 2 + 1);
                            epoll_ctl(ep, EPOLL_CTL_ADD, s.v.fd, &ev);
                        }
                        char hdr[256];
                        int hl = snprintf(
                            hdr, sizeof hdr,
                            "POST /%s HTTP/1.1\r\nHost: lg\r\n"
                            "Content-Length: %zu\r\n\r\n",
                            fid.c_str(), body_len);
                        s.v.out.assign(hdr, hl);
                        s.v.out.append(body, body_len);
                        s.v.out_off = 0;
                        s.v.in.clear();
                        s.v.expect = 0;
                        s.phase = 1;
                        mod(s.v.fd, (uint32_t)(si * 2 + 1), EPOLLIN | EPOLLOUT);
                    } else {
                        if (ok2xx) ok++;
                        else errs++;
                        done++;
                        start_assign(si);
                    }
                    continue;
                }
            }
            if (fail) fail_slot(si);
        }
    }
    uint64_t t1 = lg_now_ns();
    for (auto& s : slots) {
        if (s.m.fd >= 0) close(s.m.fd);
        if (s.v.fd >= 0) close(s.v.fd);
    }
    close(ep);
    out3[0] = ok;
    out3[1] = errs + (n_files - done);
    out3[2] = t1 - t0;
    return 0;
}

}  // extern "C"
