// GF(2^8) shard matmul: out[r] = sum_c M[r][c] * in[c] over the Rijndael-free
// polynomial 0x11D field used by Backblaze/klauspost Reed-Solomon.
// CPU equivalent of klauspost/reedsolomon's vector kernels
// (weed/storage/erasure_coding/ec_encoder.go:202): on GFNI+AVX512 hardware
// each coefficient becomes an 8x8 GF(2) bit-matrix applied 64 bytes at a
// time by VGF2P8AFFINEQB (klauspost's own fast path); otherwise a
// table-driven SWAR loop. The GFNI path is verified against the table at
// init and disabled on mismatch, so output is always byte-identical.
#include <cstdint>
#include <cstddef>
#include <cstring>
#include <vector>

#include <sys/mman.h>
#include <unistd.h>

#if defined(__GFNI__) && defined(__AVX512F__) && defined(__AVX512BW__)
#include <immintrin.h>
#define SW_HAVE_GFNI 1
#endif

namespace {

uint8_t mul_table[256][256];
bool gf_ready = false;

void init_tables() {
    uint8_t exp_t[512];
    int log_t[256];
    int x = 1;
    for (int i = 0; i < 255; i++) {
        exp_t[i] = (uint8_t)x;
        log_t[x] = i;
        x <<= 1;
        if (x & 0x100) x ^= 0x11D;
    }
    for (int i = 255; i < 512; i++) exp_t[i] = exp_t[i - 255];
    for (int a = 0; a < 256; a++) {
        mul_table[0][a] = 0;
        mul_table[a][0] = 0;
    }
    for (int a = 1; a < 256; a++)
        for (int b = 1; b < 256; b++)
            mul_table[a][b] = exp_t[log_t[a] + log_t[b]];
}

#ifdef SW_HAVE_GFNI
// 8x8 bit-matrix operand for GF2P8AFFINEQB so that affine(x, A, 0) == c*x
// in GF(2^8)/0x11D. Result bit i = parity(A.byte[7-i] & x), so byte (7-i)
// holds, per input bit k, bit i of c*2^k.
uint64_t affine_matrix(uint8_t c) {
    uint8_t p[8];
    for (int k = 0; k < 8; k++) p[k] = mul_table[c][(uint8_t)(1u << k)];
    uint64_t m = 0;
    for (int i = 0; i < 8; i++) {
        uint8_t row = 0;
        for (int k = 0; k < 8; k++) row |= (uint8_t)(((p[k] >> i) & 1) << k);
        m |= (uint64_t)row << (8 * (7 - i));
    }
    return m;
}

bool gfni_selftest() {
    alignas(64) uint8_t src[64], dst[64];
    for (int i = 0; i < 64; i++) src[i] = (uint8_t)(i * 7 + 3);
    const uint8_t coefs[4] = {2, 0x1D, 0xFF, 7};
    for (uint8_t c : coefs) {
        __m512i a = _mm512_set1_epi64((long long)affine_matrix(c));
        __m512i x = _mm512_loadu_si512((const void*)src);
        _mm512_storeu_si512((void*)dst, _mm512_gf2p8affine_epi64_epi8(x, a, 0));
        for (int i = 0; i < 64; i++)
            if (dst[i] != mul_table[c][src[i]]) return false;
    }
    return true;
}
#endif

bool gfni_ok = false;

void init_gf() {
    if (gf_ready) return;
    init_tables();
#ifdef SW_HAVE_GFNI
    if (__builtin_cpu_supports("avx512f") && __builtin_cpu_supports("avx512bw") &&
        __builtin_cpu_supports("gfni"))
        gfni_ok = gfni_selftest();
#endif
    gf_ready = true;
}

void matmul_table(const unsigned char* matrix, int rows, int cols,
                  const unsigned char** inputs, unsigned char** outputs,
                  size_t lo, size_t hi) {
    for (int r = 0; r < rows; r++) {
        unsigned char* out = outputs[r];
        std::memset(out + lo, 0, hi - lo);
        for (int c = 0; c < cols; c++) {
            uint8_t coef = matrix[r * cols + c];
            if (coef == 0) continue;
            const uint8_t* row = mul_table[coef];
            const unsigned char* in = inputs[c];
            if (coef == 1) {
                for (size_t i = lo; i < hi; i++) out[i] ^= in[i];
            } else {
                for (size_t i = lo; i < hi; i++) out[i] ^= row[in[i]];
            }
        }
    }
}

#ifdef SW_HAVE_GFNI
void matmul_gfni(const unsigned char* matrix, int rows, int cols,
                 const unsigned char** inputs, unsigned char** outputs,
                 size_t n) {
    std::vector<__m512i> am((size_t)rows * cols);
    for (int r = 0; r < rows; r++)
        for (int c = 0; c < cols; c++)
            am[(size_t)r * cols + c] =
                _mm512_set1_epi64((long long)affine_matrix(matrix[r * cols + c]));
    size_t vec_end = n & ~(size_t)63;
    __m512i x[32];
    for (size_t off = 0; off < vec_end; off += 64) {
        for (int c = 0; c < cols; c++)
            x[c] = _mm512_loadu_si512((const void*)(inputs[c] + off));
        for (int r = 0; r < rows; r++) {
            __m512i acc = _mm512_setzero_si512();
            for (int c = 0; c < cols; c++) {
                uint8_t coef = matrix[r * cols + c];
                if (coef == 0) continue;
                if (coef == 1)
                    acc = _mm512_xor_si512(acc, x[c]);
                else
                    acc = _mm512_xor_si512(
                        acc, _mm512_gf2p8affine_epi64_epi8(
                                 x[c], am[(size_t)r * cols + c], 0));
            }
            _mm512_storeu_si512((void*)(outputs[r] + off), acc);
        }
    }
    if (vec_end < n)
        matmul_table(matrix, rows, cols, inputs, outputs, vec_end, n);
}
#endif

} // namespace

extern "C" void sw_gf256_matmul(const unsigned char* matrix, int rows, int cols,
                                const unsigned char** inputs,
                                unsigned char** outputs, size_t n) {
    init_gf();
    if (rows <= 0 || cols <= 0) return;
#ifdef SW_HAVE_GFNI
    // the GFNI block loop keeps all inputs in registers and caps at 32
    // shards; wider matrices take the (unbounded) table path
    if (gfni_ok && n >= 64 && cols <= 32) {
        matmul_gfni(matrix, rows, cols, inputs, outputs, n);
        return;
    }
#endif
    matmul_table(matrix, rows, cols, inputs, outputs, 0, n);
}

// Contiguous-layout entry: in is (cols, n) row-major, out is (rows, n)
// row-major — lets callers pass numpy buffers with zero copies.
extern "C" void sw_gf256_matmul2d(const unsigned char* matrix, int rows,
                                  int cols, const unsigned char* in,
                                  unsigned char* out, size_t n) {
    if (rows <= 0 || cols <= 0) return;
    std::vector<const unsigned char*> ins(cols);
    std::vector<unsigned char*> outs(rows);
    for (int c = 0; c < cols; c++) ins[c] = in + (size_t)c * n;
    for (int r = 0; r < rows; r++) outs[r] = out + (size_t)r * n;
    sw_gf256_matmul(matrix, rows, cols, ins.data(), outs.data(), n);
}

// Row-batched EC encode over the reference's striped row layout
// (`ec_encoder.go:198-235`): `in` holds row_count consecutive rows of
// cols*block bytes straight from the .dat; parity lands as (rows,
// row_count*block) with row r2's parity at columns [r2*block, (r2+1)*block).
// One call per pipeline chunk keeps the GIL released for the whole batch.
extern "C" void sw_gf256_encode_rows(const unsigned char* matrix, int rows,
                                     int cols, const unsigned char* in,
                                     size_t block, int row_count,
                                     unsigned char* out) {
    if (rows <= 0 || cols <= 0) return;
    std::vector<const unsigned char*> ins(cols);
    std::vector<unsigned char*> outs(rows);
    size_t span = (size_t)row_count * block;
    for (int r2 = 0; r2 < row_count; r2++) {
        for (int c = 0; c < cols; c++)
            ins[c] = in + ((size_t)r2 * cols + c) * block;
        for (int r = 0; r < rows; r++)
            outs[r] = out + (size_t)r * span + (size_t)r2 * block;
        sw_gf256_matmul(matrix, rows, cols, ins.data(), outs.data(), block);
    }
}

#ifdef SW_HAVE_GFNI
namespace {

// Fused encode of one full block row: 10 data blocks stream from the mmap'd
// .dat straight through registers — each 64B line is NT-stored to its data
// shard while GF2P8AFFINEQB accumulates the 4 parity lines, which are then
// NT-stored too. One pass over memory: read 1x, write 1.4x, no user<->kernel
// copies and no cache pollution (the page-cache copies of the pread/pwrite
// pipeline cost ~2x this on a single-core host).
void encode_row_fused(const __m512i* am, int prows, int dcols,
                      const unsigned char* src, size_t block,
                      unsigned char** dst, size_t shard_off) {
    for (size_t i = 0; i < block; i += 64) {
        __m512i acc[4];
        for (int r = 0; r < prows; r++) acc[r] = _mm512_setzero_si512();
        for (int c = 0; c < dcols; c++) {
            __m512i x = _mm512_loadu_si512(
                (const void*)(src + (size_t)c * block + i));
            _mm512_stream_si512((__m512i*)(dst[c] + shard_off + i), x);
            for (int r = 0; r < prows; r++)
                acc[r] = _mm512_xor_si512(
                    acc[r], _mm512_gf2p8affine_epi64_epi8(
                                x, am[(size_t)r * dcols + c], 0));
        }
        for (int r = 0; r < prows; r++)
            _mm512_stream_si512(
                (__m512i*)(dst[dcols + r] + shard_off + i), acc[r]);
    }
}

} // namespace
#endif

// Whole-volume fused EC encode over the reference's striped row layout
// (`ec_encoder.go:198-235`): large rows while >1 full large row remains,
// then small rows with the tail zero-padded. Caller must have ftruncated
// every shard file to shard_size. Returns 0 on success, <0 when this host
// can't run the fused path (caller falls back to the staged pipeline).
extern "C" long long sw_ec_encode_volume(
    const unsigned char* matrix, int prows, int dcols, int dat_fd,
    unsigned long long total, const int* shard_fds,
    unsigned long long shard_size, unsigned long long large_block,
    unsigned long long small_block) {
#ifndef SW_HAVE_GFNI
    (void)matrix; (void)prows; (void)dcols; (void)dat_fd; (void)total;
    (void)shard_fds; (void)shard_size; (void)large_block; (void)small_block;
    return -1;
#else
    init_gf();
    if (!gfni_ok) return -1;
    if (prows <= 0 || prows > 4 || dcols <= 0 || dcols > 30) return -2;
    if (large_block % 64 || small_block % 64 || !small_block || !large_block)
        return -2;  // a zero block would spin the GIL-released row loop
    if (!total) return -2;
    int nshards = dcols + prows;

    const unsigned char* src = (const unsigned char*)mmap(
        nullptr, total, PROT_READ, MAP_SHARED | MAP_POPULATE, dat_fd, 0);
    if (src == MAP_FAILED) return -3;
    std::vector<unsigned char*> maps(nshards, nullptr);
    long long rc = 0;
    for (int s = 0; s < nshards && rc == 0; s++) {
        maps[s] = (unsigned char*)mmap(nullptr, shard_size,
                                       PROT_READ | PROT_WRITE,
                                       MAP_SHARED | MAP_POPULATE,
                                       shard_fds[s], 0);
        if (maps[s] == MAP_FAILED) { maps[s] = nullptr; rc = -4; }
    }
    if (rc == 0) {
        std::vector<__m512i> am((size_t)prows * dcols);
        for (int r = 0; r < prows; r++)
            for (int c = 0; c < dcols; c++)
                am[(size_t)r * dcols + c] = _mm512_set1_epi64(
                    (long long)affine_matrix(matrix[r * dcols + c]));
        std::vector<unsigned char> bounce;
        size_t remaining = total, dat_off = 0, shard_off = 0;
        size_t large_row = large_block * (size_t)dcols;
        size_t small_row = small_block * (size_t)dcols;
        while (remaining > large_row) {
            // full large rows only (the loop condition guarantees it)
            encode_row_fused(am.data(), prows, dcols, src + dat_off,
                             large_block, maps.data(), shard_off);
            dat_off += large_row;
            shard_off += large_block;
            remaining -= large_row;
        }
        while (remaining > 0 && rc == 0) {
            if (shard_off + small_block > shard_size) { rc = -5; break; }
            if (remaining >= small_row) {
                encode_row_fused(am.data(), prows, dcols, src + dat_off,
                                 small_block, maps.data(), shard_off);
            } else {
                // tail row: zero-padded copy, then the same fused kernel
                if (bounce.size() < small_row) bounce.resize(small_row);
                std::memset(bounce.data(), 0, small_row);
                std::memcpy(bounce.data(), src + dat_off, remaining);
                encode_row_fused(am.data(), prows, dcols, bounce.data(),
                                 small_block, maps.data(), shard_off);
            }
            dat_off += small_row;
            shard_off += small_block;
            remaining = remaining > small_row ? remaining - small_row : 0;
        }
        _mm_sfence();
        if (rc == 0 && shard_off != shard_size) rc = -5;
    }
    for (int s = 0; s < nshards; s++)
        if (maps[s]) munmap(maps[s], shard_size);
    munmap((void*)src, total);
    return rc;
#endif
}

// Fused matmul over fd-mmapped shards: out[r] = sum_c M[r][c]*in[c], with
// every input read straight from the page cache (MAP_POPULATE) instead of
// pread copies. Serves ec.rebuild (decode_matrix rows) and ec.decode.
extern "C" long long sw_gf256_matmul_fds(const unsigned char* matrix,
                                         int rows, int cols,
                                         const int* in_fds,
                                         unsigned long long n,
                                         const int* out_fds) {
    init_gf();
    if (rows <= 0 || cols <= 0 || !n) return -2;
    std::vector<const unsigned char*> ins(cols, nullptr);
    std::vector<unsigned char*> outs(rows, nullptr);
    long long rc = 0;
    for (int c = 0; c < cols && rc == 0; c++) {
        void* m = mmap(nullptr, n, PROT_READ, MAP_SHARED | MAP_POPULATE,
                       in_fds[c], 0);
        if (m == MAP_FAILED) rc = -3; else ins[c] = (const unsigned char*)m;
    }
    for (int r = 0; r < rows && rc == 0; r++) {
        void* m = mmap(nullptr, n, PROT_READ | PROT_WRITE,
                       MAP_SHARED | MAP_POPULATE, out_fds[r], 0);
        if (m == MAP_FAILED) rc = -4; else outs[r] = (unsigned char*)m;
    }
    if (rc == 0)
        sw_gf256_matmul(matrix, rows, cols, ins.data(), outs.data(), n);
    for (int c = 0; c < cols; c++)
        if (ins[c]) munmap((void*)ins[c], n);
    for (int r = 0; r < rows; r++)
        if (outs[r]) munmap(outs[r], n);
    return rc;
}

extern "C" int sw_gf256_has_gfni() {
    init_gf();
    return gfni_ok ? 1 : 0;
}

// Benchmark hook: force the scalar table path (the r1 baseline kernel) so
// the GFNI speedup can be measured against it. Returns the previous state.
extern "C" int sw_gf256_set_gfni(int enabled) {
    init_gf();
    int prev = gfni_ok ? 1 : 0;
#ifdef SW_HAVE_GFNI
    gfni_ok = enabled && __builtin_cpu_supports("avx512f") &&
              __builtin_cpu_supports("avx512bw") &&
              __builtin_cpu_supports("gfni") && gfni_selftest();
#else
    (void)enabled;
#endif
    return prev;
}
