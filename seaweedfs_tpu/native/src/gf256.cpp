// GF(2^8) shard matmul: out[r] = sum_c M[r][c] * in[c] over the Rijndael-free
// polynomial 0x11D field used by Backblaze/klauspost Reed-Solomon.
// CPU stand-in for klauspost/reedsolomon's AVX2 kernels
// (weed/storage/erasure_coding/ec_encoder.go:202). Table-driven with 64-bit
// SWAR XOR accumulate; -march=native lets the compiler autovectorize.
#include <cstdint>
#include <cstddef>
#include <cstring>
#include <vector>

namespace {

uint8_t mul_table[256][256];
bool gf_ready = false;

void init_gf() {
    if (gf_ready) return;
    uint8_t exp_t[512];
    int log_t[256];
    int x = 1;
    for (int i = 0; i < 255; i++) {
        exp_t[i] = (uint8_t)x;
        log_t[x] = i;
        x <<= 1;
        if (x & 0x100) x ^= 0x11D;
    }
    for (int i = 255; i < 512; i++) exp_t[i] = exp_t[i - 255];
    for (int a = 0; a < 256; a++) {
        mul_table[0][a] = 0;
        mul_table[a][0] = 0;
    }
    for (int a = 1; a < 256; a++)
        for (int b = 1; b < 256; b++)
            mul_table[a][b] = exp_t[log_t[a] + log_t[b]];
    gf_ready = true;
}

} // namespace

extern "C" void sw_gf256_matmul(const unsigned char* matrix, int rows, int cols,
                                const unsigned char** inputs,
                                unsigned char** outputs, size_t n) {
    init_gf();
    for (int r = 0; r < rows; r++) {
        unsigned char* out = outputs[r];
        std::memset(out, 0, n);
        for (int c = 0; c < cols; c++) {
            uint8_t coef = matrix[r * cols + c];
            if (coef == 0) continue;
            const uint8_t* row = mul_table[coef];
            const unsigned char* in = inputs[c];
            if (coef == 1) {
                for (size_t i = 0; i < n; i++) out[i] ^= in[i];
            } else {
                for (size_t i = 0; i < n; i++) out[i] ^= row[in[i]];
            }
        }
    }
}
