// SW128: fast 128-bit content-identity hash for the CDC dedup index.
//
// The dedup key only needs collision resistance against accidental (and
// casually adversarial) duplicates — the same bar xxhash/spookyhash meet
// for ZFS-class dedup — while running far faster than MD5 (which is both
// slow AND cryptographically broken for collisions, so it bought nothing
// extra as a key). MD5 stays the chunk-ETag format; this hash exists only
// inside index keys ("x<hex32>-<len>"), never on the wire.
//
// STABILITY CONTRACT: keys persist in the filer store across restarts and
// upgrades, so this function must never change behavior. Golden vectors
// are pinned in tests/test_hash_kernels.py; any change that breaks them
// must introduce a new key prefix instead.
//
// Construction (wyhash/umash-style, 8 independent mul-mix lanes):
//   per 64-byte block, lane i (i = 0..7):
//     acc[i] = rot64((acc[i] ^ w[i]) * M[i], 29) + w[(i+1) & 7]
//   The multiply diffuses within a lane; the neighbor-add propagates
//   across lanes; 8 independent chains keep the multiplier pipeline full.
//   Tail blocks are zero-padded; total length is folded into finalization
//   (so padding cannot collide with explicit zeros).
//   Finalize: pairwise 64x64->128 "mum" folds of the accumulators with
//   fresh constants, then two moremur rounds per output half.

#include <stdint.h>
#include <string.h>

#include <cstddef>

namespace {

inline uint64_t rot64(uint64_t v, int r) {
    return (v << r) | (v >> (64 - r));
}

inline uint64_t mum(uint64_t a, uint64_t b) {
    __uint128_t m = (__uint128_t)a * b;
    return (uint64_t)m ^ (uint64_t)(m >> 64);
}

inline uint64_t moremur(uint64_t x) {
    x ^= x >> 27;
    x *= 0x3C79AC492BA7B653ULL;
    x ^= x >> 33;
    x *= 0x1C69B3F74AC4AE35ULL;
    x ^= x >> 27;
    return x;
}

// odd 64-bit constants (from splitmix64 of 1..18)
constexpr uint64_t M[8] = {
    0x910A2DEC89025CC1ULL, 0xBEAA4A2FB23C9F93ULL,
    0x6BB4C5F9DF6A1E8BULL, 0x2B8347B4A49D1C07ULL,
    0xD1B54A32D192ED03ULL, 0xAEF17502108EF2D9ULL,
    0x994846F1D5CF9E8DULL, 0x70E15C9D7A53F8EFULL,
};
constexpr uint64_t F[10] = {
    0x9E3779B97F4A7C15ULL, 0xC2B2AE3D27D4EB4FULL,
    0x165667B19E3779F9ULL, 0x27D4EB2F165667C5ULL,
    0x85EBCA77C2B2AE63ULL, 0xFF51AFD7ED558CCDULL,
    0xC4CEB9FE1A85EC53ULL, 0x2545F4914F6CDD1DULL,
    0x9FB21C651E98DF25ULL, 0xD6E8FEB86659FD93ULL,
};

// Hand-unrolled lanes in named locals: gcc's AVX-512 auto-vectorization
// of the array-indexed form uses VPMULLQ (3 uops, high latency) and
// measures ~2x SLOWER than the scalar 64-bit multiplier pipeline this
// loop is designed around; explicit registers sidestep both the
// vectorizer and the acc[]/nxt[] spills.
// seed0/seed1: per-store random secret (filer/dedup.py keeps it under the
// index root). An unseeded mul-mix hash is offline-collidable — with the
// seed folded into every accumulator, an attacker cannot construct the
// colliding pair that would make a victim's upload dedup to attacker
// bytes. seed0 == seed1 == 0 reproduces the unseeded goldens.
void sw128_one(const unsigned char* p, size_t len, uint64_t seed0,
               uint64_t seed1, unsigned char out[16]) {
    uint64_t a0 = F[0] ^ (M[0] * 1) ^ seed0, a1 = F[1] ^ (M[1] * 2) ^ seed1,
             a2 = F[2] ^ (M[2] * 3) ^ rot64(seed0, 17),
             a3 = F[3] ^ (M[3] * 4) ^ rot64(seed1, 31),
             a4 = F[4] ^ (M[4] * 5) ^ rot64(seed0, 43),
             a5 = F[5] ^ (M[5] * 6) ^ rot64(seed1, 11),
             a6 = F[6] ^ (M[6] * 7) ^ (seed0 + seed1),
             a7 = F[7] ^ (M[7] * 8) ^ (seed0 ^ rot64(seed1, 53));
    size_t full = len / 64;
    uint64_t w[8];
    for (size_t b = 0; b < full; b++) {
        memcpy(w, p + b * 64, 64);  // little-endian load (x86)
        uint64_t n0 = rot64((a0 ^ w[0]) * M[0], 29) + w[1];
        uint64_t n1 = rot64((a1 ^ w[1]) * M[1], 29) + w[2];
        uint64_t n2 = rot64((a2 ^ w[2]) * M[2], 29) + w[3];
        uint64_t n3 = rot64((a3 ^ w[3]) * M[3], 29) + w[4];
        uint64_t n4 = rot64((a4 ^ w[4]) * M[4], 29) + w[5];
        uint64_t n5 = rot64((a5 ^ w[5]) * M[5], 29) + w[6];
        uint64_t n6 = rot64((a6 ^ w[6]) * M[6], 29) + w[7];
        uint64_t n7 = rot64((a7 ^ w[7]) * M[7], 29) + w[0];
        a0 = n0; a1 = n1; a2 = n2; a3 = n3;
        a4 = n4; a5 = n5; a6 = n6; a7 = n7;
    }
    size_t rem = len - full * 64;
    if (rem) {
        memset(w, 0, sizeof w);
        memcpy(w, p + full * 64, rem);
        uint64_t n0 = rot64((a0 ^ w[0]) * M[0], 29) + w[1];
        uint64_t n1 = rot64((a1 ^ w[1]) * M[1], 29) + w[2];
        uint64_t n2 = rot64((a2 ^ w[2]) * M[2], 29) + w[3];
        uint64_t n3 = rot64((a3 ^ w[3]) * M[3], 29) + w[4];
        uint64_t n4 = rot64((a4 ^ w[4]) * M[4], 29) + w[5];
        uint64_t n5 = rot64((a5 ^ w[5]) * M[5], 29) + w[6];
        uint64_t n6 = rot64((a6 ^ w[6]) * M[6], 29) + w[7];
        uint64_t n7 = rot64((a7 ^ w[7]) * M[7], 29) + w[0];
        a0 = n0; a1 = n1; a2 = n2; a3 = n3;
        a4 = n4; a5 = n5; a6 = n6; a7 = n7;
    }
    uint64_t h1 = mum(a0 ^ F[0], a1 ^ F[1]) ^ mum(a2 ^ F[2], a3 ^ F[3]) ^
                  ((uint64_t)len * F[8]);
    uint64_t h2 = mum(a4 ^ F[4], a5 ^ F[5]) ^ mum(a6 ^ F[6], a7 ^ F[7]) ^
                  (rot64((uint64_t)len, 32) * F[9]);
    uint64_t ha = moremur(h1 ^ rot64(h2, 31));
    uint64_t hb = moremur(h2 ^ rot64(ha, 29));
    memcpy(out, &ha, 8);
    memcpy(out + 8, &hb, 8);
}

}  // namespace

extern "C" {

// seed: 16 bytes (two little-endian u64) or null for the unseeded form
void sw_fast128(const unsigned char* data, size_t len,
                const unsigned char* seed, unsigned char out[16]) {
    uint64_t s0 = 0, s1 = 0;
    if (seed != nullptr) {
        memcpy(&s0, seed, 8);
        memcpy(&s1, seed + 8, 8);
    }
    sw128_one(data, len, s0, s1, out);
}

// spans of one contiguous buffer: cuts are exclusive ends ([prev, cut))
void sw_fast128_spans(const unsigned char* base, const size_t* cuts,
                      size_t n, const unsigned char* seed,
                      unsigned char* out) {
    uint64_t s0 = 0, s1 = 0;
    if (seed != nullptr) {
        memcpy(&s0, seed, 8);
        memcpy(&s1, seed + 8, 8);
    }
    size_t prev = 0;
    for (size_t i = 0; i < n; i++) {
        sw128_one(base + prev, cuts[i] - prev, s0, s1, out + i * 16);
        prev = cuts[i];
    }
}

}  // extern "C"
