// Fastlane: epoll HTTP/1.1 front door for the volume-server data plane.
//
// The reference serves its data plane from Go (one goroutine per
// connection, all cores; `weed/server/volume_server_handlers_read.go:45`,
// `_write.go:18`). A Python http.server cannot reach that under the GIL,
// so this engine owns the hot path natively inside the same process:
//
//   GET/HEAD /<vid>,<fid>       -> lock-free-ish map lookup + pread + parse
//   POST/PUT /<vid>,<fid>       -> needle encode + append + map/idx update
//   DELETE   /<vid>,<fid>       -> tombstone append
//   everything else             -> proxied verbatim to the Python backend
//                                  (admin plane, range reads, TTL writes,
//                                  overwrites, replicated volumes, JWT...)
//
// Python stays the owner of volume lifecycle: it registers volumes
// (dup'd .dat/.idx fds + a bulk map load), routes its own rare appends
// through this engine's per-volume lock/tail, and drains an event queue
// to keep its needle map in sync (storage/fastlane.py).
//
// On-disk formats written here are bit-identical to storage/needle.py
// (v2/v3 needle records) and storage/idx.py (16-byte idx entries).

#include <arpa/inet.h>
#include <ctype.h>
#include <dlfcn.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <pthread.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/uio.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <array>
#include <atomic>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <shared_mutex>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

extern "C" uint32_t sw_crc32c_update(uint32_t crc, const char* data, size_t len);
extern "C" void sw_hmac_sha256(const uint8_t* key, size_t key_len,
                               const uint8_t* data, size_t len,
                               uint8_t out[32]);
extern "C" void sw_md5_batch_var(const unsigned char* const* ptrs,
                                 const size_t* lens, size_t n,
                                 unsigned char* out);

namespace {

// ---------------------------------------------------------------------------
// TLS via dlopen'd OpenSSL 3 (this image ships libssl.so.3 but no headers).
// The engine terminates mTLS itself (`weed/security/tls.go` semantics:
// client certs REQUIRED, allowed-commonNames gate per request) so hardened
// clusters keep the native data plane instead of falling back to the
// GIL-bound Python proxy. Only the stable OpenSSL C ABI is used; every
// symbol is resolved at runtime and a resolution failure makes sw_fl_start
// report TLS-unavailable so Python serves TLS itself.
// ---------------------------------------------------------------------------

// stable ABI constants (openssl/ssl.h, openssl/obj_mac.h)
constexpr int kSSL_FILETYPE_PEM = 1;
constexpr int kSSL_VERIFY_PEER = 0x01;
constexpr int kSSL_VERIFY_FAIL_IF_NO_PEER_CERT = 0x02;
constexpr int kSSL_CTRL_MODE = 33;
constexpr long kSSL_MODE_ENABLE_PARTIAL_WRITE = 0x1;
constexpr long kSSL_MODE_ACCEPT_MOVING_WRITE_BUFFER = 0x2;
constexpr int kSSL_ERROR_WANT_READ = 2;
constexpr int kSSL_ERROR_WANT_WRITE = 3;
constexpr int kNID_commonName = 13;

struct TlsApi {
    void* (*TLS_server_method)();
    void* (*TLS_client_method)();
    void (*SSL_set_connect_state)(void*);
    void* (*SSL_CTX_new)(void*);
    void (*SSL_CTX_free)(void*);
    int (*SSL_CTX_use_certificate_chain_file)(void*, const char*);
    int (*SSL_CTX_use_PrivateKey_file)(void*, const char*, int);
    int (*SSL_CTX_load_verify_locations)(void*, const char*, const char*);
    void (*SSL_CTX_set_verify)(void*, int, void*);
    long (*SSL_CTX_ctrl)(void*, int, long, void*);
    void* (*SSL_new)(void*);
    void (*SSL_free)(void*);
    int (*SSL_set_fd)(void*, int);
    void (*SSL_set_accept_state)(void*);
    int (*SSL_do_handshake)(void*);
    int (*SSL_read)(void*, void*, int);
    int (*SSL_write)(void*, const void*, int);
    int (*SSL_get_error)(const void*, int);
    int (*SSL_shutdown)(void*);
    void* (*SSL_get1_peer_certificate)(const void*);
    void* (*X509_get_subject_name)(const void*);
    int (*X509_NAME_get_text_by_NID)(void*, int, char*, int);
    void (*X509_free)(void*);
    bool ok = false;
};

std::atomic<TlsApi*> g_tls_api{nullptr};

TlsApi* tls_api() {
    // lock-free once resolved: every TLS read/write on every worker calls
    // this, and a shared mutex here would serialize the whole data plane
    TlsApi* ready = g_tls_api.load(std::memory_order_acquire);
    if (ready != nullptr) return ready->ok ? ready : nullptr;
    static TlsApi api;
    static pthread_mutex_t mu = PTHREAD_MUTEX_INITIALIZER;
    static bool tried = false;
    pthread_mutex_lock(&mu);
    if (!tried) {
        tried = true;
        void* ssl = dlopen("libssl.so.3", RTLD_NOW | RTLD_GLOBAL);
        if (!ssl) ssl = dlopen("libssl.so.1.1", RTLD_NOW | RTLD_GLOBAL);
        void* crypto = dlopen("libcrypto.so.3", RTLD_NOW | RTLD_GLOBAL);
        if (!crypto) crypto = dlopen("libcrypto.so.1.1", RTLD_NOW | RTLD_GLOBAL);
        if (ssl && crypto) {
            bool all = true;
            auto S = [&](const char* n) -> void* {
                void* p = dlsym(ssl, n);
                if (!p) p = dlsym(crypto, n);
                if (!p) all = false;
                return p;
            };
            *(void**)&api.TLS_server_method = S("TLS_server_method");
            *(void**)&api.TLS_client_method = S("TLS_client_method");
            *(void**)&api.SSL_set_connect_state = S("SSL_set_connect_state");
            *(void**)&api.SSL_CTX_new = S("SSL_CTX_new");
            *(void**)&api.SSL_CTX_free = S("SSL_CTX_free");
            *(void**)&api.SSL_CTX_use_certificate_chain_file =
                S("SSL_CTX_use_certificate_chain_file");
            *(void**)&api.SSL_CTX_use_PrivateKey_file =
                S("SSL_CTX_use_PrivateKey_file");
            *(void**)&api.SSL_CTX_load_verify_locations =
                S("SSL_CTX_load_verify_locations");
            *(void**)&api.SSL_CTX_set_verify = S("SSL_CTX_set_verify");
            *(void**)&api.SSL_CTX_ctrl = S("SSL_CTX_ctrl");
            *(void**)&api.SSL_new = S("SSL_new");
            *(void**)&api.SSL_free = S("SSL_free");
            *(void**)&api.SSL_set_fd = S("SSL_set_fd");
            *(void**)&api.SSL_set_accept_state = S("SSL_set_accept_state");
            *(void**)&api.SSL_do_handshake = S("SSL_do_handshake");
            *(void**)&api.SSL_read = S("SSL_read");
            *(void**)&api.SSL_write = S("SSL_write");
            *(void**)&api.SSL_get_error = S("SSL_get_error");
            *(void**)&api.SSL_shutdown = S("SSL_shutdown");
            // OpenSSL 3 renamed it (get1 = caller owns the ref); 1.1 name
            // has identical semantics for our use
            void* g = dlsym(ssl, "SSL_get1_peer_certificate");
            if (!g) g = dlsym(ssl, "SSL_get_peer_certificate");
            if (!g) all = false;
            *(void**)&api.SSL_get1_peer_certificate = g;
            *(void**)&api.X509_get_subject_name = S("X509_get_subject_name");
            *(void**)&api.X509_NAME_get_text_by_NID =
                S("X509_NAME_get_text_by_NID");
            *(void**)&api.X509_free = S("X509_free");
            api.ok = all;
        }
        g_tls_api.store(&api, std::memory_order_release);
    }
    pthread_mutex_unlock(&mu);
    return api.ok ? &api : nullptr;
}

// '*'-wildcard match, same semantics as security/tls.py compile_cn_pattern
bool glob_match(const char* pat, const char* s) {
    if (*pat == 0) return *s == 0;
    if (*pat == '*') {
        for (const char* t = s;; t++) {
            if (glob_match(pat + 1, t)) return true;
            if (*t == 0) return false;
        }
    }
    return *pat == *s && glob_match(pat + 1, s + 1);
}

// ---------------------------------------------------------------------------
// needle map: open addressing, u64 key -> (offset bytes u64, size i32)
// ---------------------------------------------------------------------------

struct NMap {
    struct Slot { uint64_t key; uint64_t off; int32_t size; uint8_t state; };
    // state: 0 empty, 1 live, 2 hole (deleted; key kept for probing)
    std::vector<Slot> slots;
    size_t live = 0, used = 0;

    NMap() { slots.resize(1024); }

    static uint64_t hash(uint64_t k) {
        k ^= k >> 33; k *= 0xff51afd7ed558ccdULL; k ^= k >> 33;
        k *= 0xc4ceb9fe1a85ec53ULL; k ^= k >> 33; return k;
    }
    void grow() {
        std::vector<Slot> old;
        old.swap(slots);
        slots.resize(old.size() * 2);
        used = live = 0;  // place() recounts while replaying live entries
        for (auto& s : old)
            if (s.state == 1) place(s.key, s.off, s.size);
    }
    void place(uint64_t key, uint64_t off, int32_t size) {
        size_t mask = slots.size() - 1;
        size_t i = hash(key) & mask;
        while (slots[i].state == 1 && slots[i].key != key) i = (i + 1) & mask;
        if (slots[i].state != 1) { if (slots[i].state == 0) used++; live++; }
        slots[i] = {key, off, size, 1};
    }
    void put(uint64_t key, uint64_t off, int32_t size) {
        if ((used + 1) * 10 >= slots.size() * 7) grow();
        // overwrite-in-place if present (incl. reviving a hole)
        size_t mask = slots.size() - 1;
        size_t i = hash(key) & mask;
        size_t first_hole = SIZE_MAX;
        while (slots[i].state != 0) {
            if (slots[i].key == key) {
                if (slots[i].state != 1) live++;
                slots[i].off = off; slots[i].size = size; slots[i].state = 1;
                return;
            }
            if (slots[i].state == 2 && first_hole == SIZE_MAX) first_hole = i;
            i = (i + 1) & mask;
        }
        if (first_hole != SIZE_MAX) i = first_hole; else used++;
        slots[i] = {key, off, size, 1};
        live++;
    }
    bool get(uint64_t key, uint64_t* off, int32_t* size) const {
        size_t mask = slots.size() - 1;
        size_t i = hash(key) & mask;
        while (slots[i].state != 0) {
            if (slots[i].state == 1 && slots[i].key == key) {
                *off = slots[i].off; *size = slots[i].size; return true;
            }
            i = (i + 1) & mask;
        }
        return false;
    }
    bool del(uint64_t key) {
        size_t mask = slots.size() - 1;
        size_t i = hash(key) & mask;
        while (slots[i].state != 0) {
            if (slots[i].state == 1 && slots[i].key == key) {
                slots[i].state = 2; live--; return true;
            }
            i = (i + 1) & mask;
        }
        return false;
    }
};

// ---------------------------------------------------------------------------
// volume registry
// ---------------------------------------------------------------------------

struct Vol {
    uint32_t vid;
    int dat_fd = -1, idx_fd = -1;
    int version = 3;
    std::atomic<bool> serving{false};  // false until the map bulk-load lands
    std::atomic<uint64_t> tail{0};
    std::atomic<uint64_t> last_ns{0};
    std::atomic<bool> readonly{false};
    std::atomic<bool> forward_writes{false};
    // online-EC stripe accumulator (sw_fl_ec_online_*): the Python-side
    // striper arms stripe_bytes + its encode watermark; the drain loop
    // polls readiness in O(1) off the append tail instead of draining
    // events just to learn nothing new accumulated. 0 = not armed.
    std::atomic<uint64_t> ec_stripe{0};
    std::atomic<uint64_t> ec_watermark{0};
    // per-volume native-op counters (sw_fl_get_volume_metrics)
    std::atomic<uint64_t> m_reads{0}, m_writes{0}, m_deletes{0},
        m_read_bytes{0}, m_write_bytes{0};
    // tenant tag for sw_fl_get_usage; guarded by Engine::reg_mu, not an
    // atomic — it is written once at registration time before traffic
    char collection[64] = {0};
    std::mutex append_mu;           // serializes .dat appends (C++ and Python)
    std::shared_mutex map_mu;       // guards nmap
    NMap nmap;
    ~Vol() {
        if (dat_fd >= 0) close(dat_fd);
        if (idx_fd >= 0) close(idx_fd);
    }
};

struct Event {  // mirrored by storage/fastlane.py (48 bytes, little-endian)
    uint32_t vid;
    uint32_t op;        // 0 put, 1 delete-tombstone
    uint64_t key;
    uint64_t offset;    // byte offset of the written record
    int32_t size;       // needle body size (put) or freed size (delete)
    uint32_t pad;
    uint64_t append_ns;
    uint64_t trace_id;  // X-Sw-Trace-Id of the originating request (0=none):
                        // drain-synthesized spans join the caller's trace
};

struct Engine;
std::vector<Engine*> g_engines;   // slot per started engine; null when stopped
std::mutex g_engine_mu;

Engine* engine_at(int h) {
    std::lock_guard<std::mutex> gl(g_engine_mu);
    if (h < 0 || (size_t)h >= g_engines.size()) return nullptr;
    return g_engines[h];
}

struct Stats {
    std::atomic<uint64_t> requests{0}, native_reads{0}, native_writes{0},
        native_deletes{0}, native_assigns{0}, proxied{0};
};

// --- per-op engine metrics ---------------------------------------------------
// Fixed-bucket latency histograms + byte counters, all relaxed atomics so
// the hot path pays a handful of uncontended fetch_adds. Host profilers
// cannot see into this engine's epoll loop, so it carries its own
// instrumentation surface, exported raw through sw_fl_get_metrics and
// rendered into Prometheus families by the Python side.

constexpr int kOpRead = 0, kOpWrite = 1, kOpDelete = 2, kOpAssign = 3,
              kOpProxy = 4;
constexpr int kNumOps = 5;
constexpr int kLatBuckets = 16;
// finite bucket upper bounds in ns (50us..5s); each OpStat carries one
// extra overflow slot that Python renders as +Inf
constexpr uint64_t kLatBoundsNs[kLatBuckets] = {
    50000ull,      100000ull,     250000ull,     500000ull,
    1000000ull,    2500000ull,    5000000ull,    10000000ull,
    25000000ull,   50000000ull,   100000000ull,  250000000ull,
    500000000ull,  1000000000ull, 2500000000ull, 5000000000ull,
};

struct OpStat {
    std::atomic<uint64_t> count{0}, bytes{0}, ns_sum{0};
    std::atomic<uint64_t> buckets[kLatBuckets + 1] = {};

    void observe(uint64_t ns, uint64_t nbytes) {
        count.fetch_add(1, std::memory_order_relaxed);
        if (nbytes) bytes.fetch_add(nbytes, std::memory_order_relaxed);
        ns_sum.fetch_add(ns, std::memory_order_relaxed);
        int i = 0;
        while (i < kLatBuckets && ns > kLatBoundsNs[i]) i++;
        buckets[i].fetch_add(1, std::memory_order_relaxed);
    }
};

// ---------------------------------------------------------------------------
// HTTP connection state
// ---------------------------------------------------------------------------

struct BackendConn;

struct Conn {
    int kind = 0;        // epoll data discriminator: 0 = client connection
    int fd = -1;
    std::string in;      // accumulated request bytes
    std::string out;     // pending response bytes
    size_t out_off = 0;
    // zero-copy body channel: large response bodies ride here instead of
    // being memcpy'd into `out` — flush_out sends headers + body with one
    // writev. Either an owned buffer (out2, moved in) or a pinned shared
    // one (out2_pin keeps it alive); out2_data/len point at the bytes.
    std::string out2;
    std::shared_ptr<const void> out2_pin;
    const char* out2_data = nullptr;
    size_t out2_len = 0, out2_off = 0;
    bool want_close = false;
    bool sent_continue = false;  // answered Expect: 100-continue this request
    size_t chunk_scan = 0;       // chunked decode: resume position in `in`
    std::string chunk_body;      // chunked decode: body decoded so far
    BackendConn* upstream = nullptr;  // pending proxied request, if any
    uint64_t req_start_ns = 0;   // mono_ns at dispatch of the current request
    time_t last_active = 0;
    void* ssl = nullptr;  // OpenSSL SSL* when the engine terminates TLS
    int tls_hs = 0;       // 0 plaintext, 1 handshaking, 2 established
    bool cn_ok = true;    // false: CA-valid cert, disallowed CommonName
};

// One in-flight upstream request. The worker never blocks on it: the
// upstream socket sits in the same epoll and this struct is the parse
// state machine for its response. Targets the Python backend by default;
// filer mode also points these at volume servers (chunk uploads, read
// relays) — `mode` picks the completion handler.
struct BackendConn {
    int kind = 1;
    int fd = -1;
    bool counted = false;     // holds a slot under the backend cap
    bool head_request = false;  // HEAD: response framing carries no body
    Conn* client = nullptr;   // null if the client went away mid-flight
    std::string req;          // original request bytes (kept for one retry)
    size_t req_off = 0;       // send progress
    std::string resp;
    size_t hdr_end = 0;       // 0 until headers parsed
    size_t body_need = 0;     // with content-length: total expected bytes
    int body_mode = 0;        // 0 unknown, 1 content-length, 2 chunked, 3 to-EOF
    size_t chunk_pos = 0;     // chunked scan cursor
    bool backend_close = false;
    bool retried = false;
    bool from_pool = false;   // current fd came from the idle keep-alive pool
    time_t started = 0;
    uint64_t start_ns = 0;    // mono_ns at proxy launch (latency metrics)
    uint32_t target_ip = 0;   // 0 = engine's default Python backend
    int target_port = 0;
    int mode = 0;             // 0 proxy, 1 filer chunk upload, 2 filer relay,
                              // 3 s3 get relay, 4 s3 put relay, 5 s3 delete
    void* ssl = nullptr;      // TLS client session (mTLS upstream hops)
    uint32_t armed = 0;       // current epoll interest mask
    // filer-write context (mode 1) / relay fallback (mode 2)
    std::string f_path, f_fid, f_mime, f_md5hex;
    uint64_t f_size = 0;
    uint64_t f_mtime = 0;
    uint64_t f_trace = 0;     // trace id riding the upstream hop
    std::shared_ptr<struct FilerLease> f_lease;  // lease that minted f_fid:
                              // an upload failure drops THIS lease only
    std::string client_req;   // original client request (fallback replay)
};

struct Worker {
    int epfd = -1;
    // keep-alive conns not currently in epoll: (fd, SSL* or null).
    // idle_backends: the engine's Python backend (always plaintext);
    // idle_targets: other targets (volume engines), keyed ip<<16|port —
    // the TLS session must live as long as its socket
    std::vector<std::pair<int, void*>> idle_backends;
    std::unordered_map<uint64_t, std::vector<std::pair<int, void*>>>
        idle_targets;
    std::vector<BackendConn*> pending;  // in-flight proxied requests
    size_t capped_inflight = 0;         // pending entries counted under the cap
    std::deque<BackendConn*> waiting;   // queued: backend concurrency capped
    std::mutex conns_mu;            // acceptor adds, worker removes
    std::vector<Conn*> conns;       // for idle sweep / teardown
    std::vector<Conn*> graveyard;   // closed this loop pass; freed next pass
    std::vector<BackendConn*> back_graveyard;
    pthread_t thread;
};

// Prebuilt assign responder for one exact /dir/assign query string: the
// Python master computes the eligible volume set + a leased file-key range
// and installs it; the engine then mints fids round-robin without Python.
struct AssignProfile {
    std::vector<uint32_t> vids;
    std::vector<std::string> tails;  // per-volume JSON after the fid field
    std::atomic<uint64_t> next_key{0};
    uint64_t end_key = 0;
    std::atomic<uint64_t> rr{0};
};

// ---------------------------------------------------------------------------
// filer mode: native small-file write path + path->location read cache
// (VERDICT r4 next #3 — the filer was GIL-capped at ~3k req/s while the
// volume plane it feeds does 60k/95k). Reference hot path:
// `weed/server/filer_server_handlers_write_autochunk.go:26-155`.
// ---------------------------------------------------------------------------

// one cached file location: either inline bytes (small content, served
// straight from memory) or a single plain chunk on a volume server
// (served by natively relaying to that server's engine)
struct FilerCacheEnt {
    uint32_t ip = 0;
    int port = 0;
    std::string fid;
    std::string inline_data;  // non-empty => inline entry
    std::string mime, md5_hex;
    uint64_t size = 0;
    uint64_t mtime = 0;  // seconds
    uint64_t seq = 0;    // FIFO generation: stale queue entries are no-ops
    bool tombstone = false;  // natively-acked DELETE not yet drained:
                             // read-your-deletes across engine cores
};

// leased fid range from the master (one /dir/assign?count=N): the engine
// mints fids locally so a native write costs zero master round-trips.
// The engine holds a POOL of these (one per volume) refreshed by Python —
// chunk writes round-robin across live leases instead of stalling on one
// spent range, and a failed volume drops only its own lease.
struct FilerLease {
    uint32_t vol_ip = 0;
    int vol_port = 0;
    uint32_t vid = 0;
    uint32_t cookie = 0;
    std::atomic<uint64_t> next_key{0};
    uint64_t end_key = 0;
    std::string auth;  // Authorization value for uploads ("" = none)
};

// front-door accounting: every data-plane-shaped request on a filer/S3
// front either serves natively or falls back to the Python proxy for a
// REASON — exported via sw_fl_front_metrics so a silent fallback regime
// (like r05's rejected lease) is a metric + alert, not a log line.
constexpr int kFrRead = 0, kFrWrite = 1, kFrDelete = 2;
constexpr int kNumFrontOps = 3;
constexpr int kFbCacheMiss = 0, kFbNoLease = 1, kFbLeaseSpent = 2,
              kFbTooLarge = 3, kFbBodyShape = 4, kFbSystemPath = 5,
              kFbQuery = 6, kFbBackpressure = 7, kFbUpstream = 8,
              kFbAuth = 9, kFbBucketState = 10, kFbOther = 11;
constexpr int kNumFbReasons = 12;

// per-bucket native permission bits (sw_fl_s3_bucket_set)
constexpr int kS3Read = 1, kS3Write = 2, kS3Delete = 4;

struct Engine {
    int listen_fd = -1;
    int port = 0;
    int backend_port = 0;
    uint32_t backend_ip = 0;  // where the Python service listens
    // ceiling on concurrent proxied requests per worker: a GIL-bound
    // backend serves N requests faster than 4N threads convoying
    size_t max_backend = 16;
    bool secure_writes = false;     // JWT configured -> proxy writes
    bool secure_reads = false;
    std::string jwt_write_key;      // non-empty: verify HS256 write JWTs natively
    std::string jwt_read_key;       // non-empty: verify read JWTs natively too
    void* tls_ctx = nullptr;        // OpenSSL SSL_CTX* (engine-terminated mTLS)
    void* tls_client_ctx = nullptr;  // client ctx: upstream hops under mTLS
    std::vector<std::string> allowed_cns;  // '*'-glob CommonName allow-list
    std::atomic<bool> running{true};
    std::deque<Worker> workers;  // deque: Worker holds mutexes, never moves
    pthread_t accept_thread;
    std::shared_mutex reg_mu;
    std::unordered_map<uint32_t, std::shared_ptr<Vol>> vols;
    std::shared_mutex assign_mu;
    std::unordered_map<std::string, std::shared_ptr<AssignProfile>> assigns;
    std::mutex ev_mu;
    std::deque<Event> events;
    Stats stats;
    OpStat op_stats[kNumOps];

    // --- filer mode ---
    std::atomic<bool> filer_mode{false};
    size_t filer_chunk_limit = 4 << 20;  // larger bodies proxy (multi-chunk)
    size_t filer_inline_limit = 2048;    // SMALL_CONTENT_LIMIT (filer.py)
    bool filer_compress = false;  // Python would compress some mimes >inline
    int filer_journal_fd = -1;
    std::mutex filer_mu;                 // journal append + event frames
    std::deque<std::string> filer_events;
    size_t filer_events_bytes = 0;
    std::shared_mutex fcache_mu;
    std::unordered_map<std::string, std::shared_ptr<FilerCacheEnt>> fcache;
    size_t fcache_inline_bytes = 0;
    uint64_t fcache_seq = 0;
    std::deque<std::pair<std::string, uint64_t>> fcache_fifo;  // (path, seq)
    std::shared_mutex flease_mu;
    // lease POOL, one entry per volume (sw_fl_filer_lease_set upserts by
    // vid): chunk writes round-robin across unspent leases, and an upload
    // failure drops only the failed volume's lease
    std::vector<std::shared_ptr<FilerLease>> fleases;
    std::atomic<uint64_t> flease_rr{0};
    std::string filer_read_auth;  // wildcard read JWT for relays (guarded
                                  // by flease_mu; refreshed with the lease)
    std::shared_mutex frules_mu;
    // fs.configure location prefixes: writes under them carry per-path
    // storage rules only the Python pipeline resolves
    std::vector<std::string> frule_prefixes;

    // --- s3 front mode ---
    // The gateway's engine relays gated object GET/PUT/DELETE straight to
    // the FILER's engine front door (protocol translation only — auth'd /
    // versioned / policied / meta-carrying requests all fall back to the
    // Python handlers, which keep the full S3 surface).
    std::atomic<bool> s3_mode{false};
    uint32_t s3_filer_ip = 0;
    int s3_filer_port = 0;
    std::shared_mutex s3_mu;
    std::unordered_map<std::string, int> s3_buckets;  // bucket -> flag bits
    std::unordered_set<std::string> s3_uploads;  // "<bucket>/<uploadId>"

    // front-door accounting (filer + s3 modes)
    std::atomic<uint64_t> fr_native[kNumFrontOps] = {};
    std::atomic<uint64_t> fr_fallback[kNumFrontOps][kNumFbReasons] = {};

    // any-state lookup (registration plumbing)
    std::shared_ptr<Vol> vol_raw(uint32_t vid) {
        std::shared_lock<std::shared_mutex> l(reg_mu);
        auto it = vols.find(vid);
        return it == vols.end() ? nullptr : it->second;
    }
    // request-path lookup: a volume whose map is still bulk-loading is
    // treated as absent so its traffic proxies to Python
    std::shared_ptr<Vol> vol(uint32_t vid) {
        auto v = vol_raw(vid);
        return (v && v->serving.load(std::memory_order_acquire)) ? v : nullptr;
    }
    void push_event(const Event& e) {
        std::lock_guard<std::mutex> l(ev_mu);
        events.push_back(e);
    }
};

// ---------------------------------------------------------------------------
// small helpers
// ---------------------------------------------------------------------------

uint64_t now_ns() {
    struct timespec ts;
    clock_gettime(CLOCK_REALTIME, &ts);
    return (uint64_t)ts.tv_sec * 1000000000ull + ts.tv_nsec;
}

void front_native_inc(Engine* E, int op) {
    E->fr_native[op].fetch_add(1, std::memory_order_relaxed);
}
void front_fb_inc(Engine* E, int op, int reason) {
    E->fr_fallback[op][reason].fetch_add(1, std::memory_order_relaxed);
}

// round-robin over the lease pool, atomically minting one key from the
// first unspent range; null when the pool is empty (*reason=kFbNoLease)
// or fully spent (*reason=kFbLeaseSpent) — the caller proxies and the
// Python side re-leases against live topology
std::shared_ptr<FilerLease> take_filer_lease(Engine* E, uint64_t* key,
                                             int* reason) {
    std::shared_lock<std::shared_mutex> l(E->flease_mu);
    size_t n = E->fleases.size();
    if (n == 0) {
        *reason = kFbNoLease;
        return nullptr;
    }
    size_t start = E->flease_rr.fetch_add(1, std::memory_order_relaxed);
    for (size_t i = 0; i < n; i++) {
        auto& L = E->fleases[(start + i) % n];
        uint64_t k = L->next_key.fetch_add(1, std::memory_order_relaxed);
        if (k < L->end_key) {
            *key = k;
            return L;
        }
    }
    *reason = kFbLeaseSpent;
    return nullptr;
}

// a failed upload condemns ONLY the lease that minted its fid (the volume
// died / moved / was deleted); the other volumes' leases keep serving
void drop_filer_lease(Engine* E, const std::shared_ptr<FilerLease>& L) {
    if (!L) return;
    std::unique_lock<std::shared_mutex> l(E->flease_mu);
    for (size_t i = 0; i < E->fleases.size(); i++)
        if (E->fleases[i] == L) {
            E->fleases.erase(E->fleases.begin() + i);
            return;
        }
}

// parse a 16-hex-char X-Sw-Trace-Id into the u64 that rides Event frames
// (stats/trace.py ids are os.urandom(8).hex()); 0 = absent/foreign format
uint64_t parse_trace_id(const std::string& s) {
    if (s.empty() || s.size() > 16) return 0;
    uint64_t v = 0;
    for (char c : s) {
        if (!isxdigit((unsigned char)c)) return 0;
        v = (v << 4) | (uint64_t)(c >= '0' && c <= '9' ? c - '0'
                                  : (c | 0x20) - 'a' + 10);
    }
    return v;
}

uint64_t mono_ns() {  // latency measurement must not jump with wall time
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (uint64_t)ts.tv_sec * 1000000000ull + ts.tv_nsec;
}

// record one completed engine-served request into the per-op metrics;
// c->req_start_ns was stamped when dispatch picked the request up, so
// async completions (filer relays/uploads) include their upstream hop
void observe_op(Engine* E, Conn* c, int op, uint64_t nbytes) {
    E->op_stats[op].observe(mono_ns() - c->req_start_ns, nbytes);
}

void put_u32be(uint8_t* p, uint32_t v) {
    p[0] = v >> 24; p[1] = v >> 16; p[2] = v >> 8; p[3] = v;
}
void put_u64be(uint8_t* p, uint64_t v) {
    put_u32be(p, v >> 32); put_u32be(p + 4, (uint32_t)v);
}
uint32_t get_u32be(const uint8_t* p) {
    return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
           ((uint32_t)p[2] << 8) | p[3];
}
uint64_t get_u64be(const uint8_t* p) {
    return ((uint64_t)get_u32be(p) << 32) | get_u32be(p + 4);
}

bool set_nonblock(int fd) {
    int fl = fcntl(fd, F_GETFL, 0);
    return fl >= 0 && fcntl(fd, F_SETFL, fl | O_NONBLOCK) == 0;
}

// TLS-aware client-socket IO. Returns >0 bytes moved, 0 peer closed,
// -1 would-block (retry on the next read event), -2 hard error,
// -3 would-block on WRITE (TLS renegotiation/KeyUpdate with a full send
// buffer: the caller must arm EPOLLOUT or the conn stalls).
int conn_read(Conn* c, char* buf, int n) {
    if (c->ssl == nullptr) {
        ssize_t r = recv(c->fd, buf, n, 0);
        if (r > 0) return (int)r;
        if (r == 0) return 0;
        return (errno == EAGAIN || errno == EWOULDBLOCK) ? -1 : -2;
    }
    TlsApi* T = tls_api();
    int r = T->SSL_read(c->ssl, buf, n);
    if (r > 0) return r;
    int e = T->SSL_get_error(c->ssl, r);
    if (e == kSSL_ERROR_WANT_READ) return -1;
    if (e == kSSL_ERROR_WANT_WRITE) return -3;
    return r == 0 ? 0 : -2;  // clean TLS shutdown reads as EOF
}

int conn_write(Conn* c, const char* buf, int n) {
    if (c->ssl == nullptr) {
        ssize_t r = send(c->fd, buf, n, MSG_NOSIGNAL);
        if (r >= 0) return (int)r;
        return (errno == EAGAIN || errno == EWOULDBLOCK) ? -1 : -2;
    }
    TlsApi* T = tls_api();
    int r = T->SSL_write(c->ssl, buf, n);
    if (r > 0) return r;
    int e = T->SSL_get_error(c->ssl, r);
    if (e == kSSL_ERROR_WANT_READ || e == kSSL_ERROR_WANT_WRITE) return -1;
    return -2;
}

// upstream-socket IO (mTLS hops to volume engines ride a TLS CLIENT
// session; SSL_read/SSL_write drive the handshake implicitly on the
// nonblocking fd). Returns >0 bytes, 0 EOF, -1 wait-for-READ,
// -3 wait-for-WRITE, -2 hard error.
int back_recv(struct BackendConn* b, char* buf, int n);
int back_send(struct BackendConn* b, const char* buf, int n);

// case-insensitive header lookup inside [hdr_begin, hdr_end); returns value
// with surrounding spaces trimmed, or empty string
std::string find_header(const char* b, const char* e, const char* name) {
    size_t nlen = strlen(name);
    const char* p = b;
    while (p < e) {
        const char* eol = (const char*)memchr(p, '\n', e - p);
        if (!eol) break;
        const char* colon = (const char*)memchr(p, ':', eol - p);
        if (colon && (size_t)(colon - p) == nlen && strncasecmp(p, name, nlen) == 0) {
            const char* v = colon + 1;
            const char* ve = eol;
            if (ve > v && ve[-1] == '\r') ve--;
            while (v < ve && (*v == ' ' || *v == '\t')) v++;
            while (ve > v && (ve[-1] == ' ' || ve[-1] == '\t')) ve--;
            return std::string(v, ve - v);
        }
        p = eol + 1;
    }
    return "";
}

void json_escape(const std::string& s, std::string& out) {
    for (unsigned char c : s) {
        switch (c) {
            case '"': out += "\\\""; break;
            case '\\': out += "\\\\"; break;
            case '\n': out += "\\n"; break;
            case '\r': out += "\\r"; break;
            case '\t': out += "\\t"; break;
            default:
                if (c < 0x20) {
                    char buf[8];
                    snprintf(buf, sizeof buf, "\\u%04x", c);
                    out += buf;
                } else out += (char)c;
        }
    }
}

// parse "<vid>,<hexkey+cookie8>[_delta]" -> ok
bool parse_fid(const char* p, const char* end, uint32_t* vid, uint64_t* key,
               uint32_t* cookie) {
    // vid digits
    uint64_t v = 0;
    const char* q = p;
    while (q < end && *q >= '0' && *q <= '9') { v = v * 10 + (*q - '0'); q++; }
    if (q == p || q >= end || *q != ',' || v > 0xFFFFFFFFull) return false;
    q++;
    // hex run
    const char* h0 = q;
    while (q < end && isxdigit((unsigned char)*q)) q++;
    size_t hlen = q - h0;
    if (hlen <= 8 || hlen > 24) return false;  // cookie is 8 hex; key 1..16
    uint64_t delta = 0;
    if (q < end && *q == '_') {
        q++;
        const char* d0 = q;
        while (q < end && *q >= '0' && *q <= '9') { delta = delta * 10 + (*q - '0'); q++; }
        if (q == d0) return false;
    }
    // optional .ext
    if (q < end && *q == '.') {
        q++;
        while (q < end && *q != '/' ) q++;
    }
    if (q != end) return false;
    auto hexval = [](char c) -> uint64_t {
        if (c >= '0' && c <= '9') return c - '0';
        if (c >= 'a' && c <= 'f') return c - 'a' + 10;
        return c - 'A' + 10;
    };
    uint64_t k = 0;
    for (size_t i = 0; i < hlen - 8; i++) k = (k << 4) | hexval(h0[i]);
    uint32_t ck = 0;
    for (size_t i = hlen - 8; i < hlen; i++) ck = (ck << 4) | (uint32_t)hexval(h0[i]);
    *vid = (uint32_t)v;
    *key = k + delta;
    *cookie = ck;
    return true;
}

int padding_len(int32_t size, int version) {
    int fixed = 16 + size + 4 + (version == 3 ? 8 : 0);
    return 8 - (fixed % 8);  // always 1..8
}
int64_t actual_size(int32_t size, int version) {
    return 16 + size + 4 + (version == 3 ? 8 : 0) + padding_len(size, version);
}

// RFC 7233 single-range parse shared by every native read surface.
// Returns 0 valid (start/end set), -1 unintelligible (serve full entity,
// both the Python handlers and handle_read ignore such specs), 1 valid
// syntax but unsatisfiable (start past end after clamping).
int parse_range_spec(const std::string& range, uint64_t total,
                     long long* start, long long* end) {
    if (range.rfind("bytes=", 0) != 0) return -1;
    const char* spec = range.c_str() + 6;
    const char* dash = strchr(spec, '-');
    if (dash == nullptr) return -1;
    for (const char* q = spec; q < dash; q++)
        if (!isdigit((unsigned char)*q)) return -1;
    for (const char* q = dash + 1; *q; q++)
        if (!isdigit((unsigned char)*q)) return -1;
    if (dash == spec && !*(dash + 1)) return -1;  // bare "bytes=-"
    if (dash != spec) {  // "start-" or "start-end"
        *start = atoll(spec);
        *end = *(dash + 1) ? atoll(dash + 1) : (long long)total - 1;
    } else {  // "-suffix": last N bytes
        long long sfx = atoll(dash + 1);
        *start = (long long)total - sfx;
        if (*start < 0) *start = 0;
        *end = (long long)total - 1;
    }
    if (*end > (long long)total - 1) *end = (long long)total - 1;
    return *start <= *end ? 0 : 1;
}

void append_response(Conn* c, int status, const char* reason,
                     const std::string& ctype,
                     const std::string& extra_headers,
                     const char* body, size_t body_len, bool head) {
    char hdr[512];
    int n = snprintf(hdr, sizeof hdr,
                     "HTTP/1.1 %d %s\r\nContent-Length: %zu\r\n", status,
                     reason, body_len);
    c->out.append(hdr, n);
    if (!ctype.empty()) {
        c->out += "Content-Type: ";
        c->out += ctype;
        c->out += "\r\n";
    }
    c->out += extra_headers;
    c->out += "\r\n";
    if (!head && body_len) c->out.append(body, body_len);
}

void json_response(Conn* c, int status, const char* reason,
                   const std::string& body) {
    append_response(c, status, reason, "application/json", "", body.data(),
                    body.size(), false);
}

// defined next to flush_out (they share the out/out2 lane layout)
void respond_zc_owned(Conn* c, int status, const char* reason,
                      const std::string& ctype, const std::string& extra,
                      std::string&& body, size_t off, size_t n);
void respond_zc_pinned(Conn* c, int status, const char* reason,
                       const std::string& ctype, const std::string& extra,
                       std::shared_ptr<const void> pin, const char* data,
                       size_t n);

// bodies at least this large ride the zero-copy out2 channel; smaller
// ones are cheaper to memcpy into the header buffer than to writev
constexpr size_t kZeroCopyMin = 4096;

// ---------------------------------------------------------------------------
// native read
// ---------------------------------------------------------------------------

bool handle_read(Engine* E, Conn* c, std::shared_ptr<Vol>& v, uint64_t key,
                 uint32_t cookie, bool head, const std::string& range) {
    uint64_t off; int32_t size;
    {
        std::shared_lock<std::shared_mutex> l(v->map_mu);
        if (!v->nmap.get(key, &off, &size) || size <= 0) {
            append_response(c, 404, "Not Found", "", "", "", 0, false);
            return true;
        }
    }
    int64_t total = actual_size(size, v->version);
    std::string blob;
    blob.resize(total);
    ssize_t got = pread(v->dat_fd, &blob[0], total, off);
    if (got < total) {
        json_response(c, 500, "Internal Server Error",
                      "{\"error\": \"short read\"}");
        return true;
    }
    const uint8_t* b = (const uint8_t*)blob.data();
    uint32_t rcookie = get_u32be(b);
    if (rcookie != cookie) {
        append_response(c, 404, "Not Found", "", "", "", 0, false);
        return true;
    }
    int32_t rsize = (int32_t)get_u32be(b + 12);
    if (rsize != size) {
        json_response(c, 500, "Internal Server Error",
                      "{\"error\": \"size mismatch\"}");
        return true;
    }
    // body parse (needle.py _read_body_v2)
    const uint8_t* body = b + 16;
    const uint8_t* bend = body + size;
    if (body + 4 > bend) {
        json_response(c, 500, "Internal Server Error",
                      "{\"error\": \"truncated needle\"}");
        return true;
    }
    uint32_t data_size = get_u32be(body);
    const uint8_t* data = body + 4;
    if (data + data_size > bend) {
        json_response(c, 500, "Internal Server Error",
                      "{\"error\": \"needle data out of range\"}");
        return true;
    }
    const uint8_t* p = data + data_size;
    uint8_t flags = p < bend ? *p : 0;
    p += 1;
    std::string name, mime;
    if ((flags & 0x02) && p < bend) {               // HAS_NAME
        uint8_t nl = *p++;
        if (p + nl <= bend) name.assign((const char*)p, nl);
        p += nl;
    }
    if ((flags & 0x04) && p < bend) {               // HAS_MIME
        uint8_t ml = *p++;
        if (p + ml <= bend) mime.assign((const char*)p, ml);
        p += ml;
    }
    uint64_t last_modified = 0;
    if ((flags & 0x08) && p + 5 <= bend) {          // HAS_LAST_MODIFIED
        for (int i = 0; i < 5; i++) last_modified = (last_modified << 8) | p[i];
        p += 5;
    }
    if (flags & 0x10) {                              // HAS_TTL
        if (p + 2 <= bend) {
            uint32_t count = p[0], unit = p[1];
            static const uint64_t mins[7] = {0, 1, 60, 1440, 10080, 43200, 525600};
            uint64_t m = unit < 7 ? mins[unit] : 0;
            if (count && m && (flags & 0x08)) {
                uint64_t expires = last_modified + count * m * 60;
                if (expires < (uint64_t)time(nullptr)) {
                    append_response(c, 404, "Not Found", "", "", "", 0, false);
                    return true;
                }
            }
        }
        p += 2;
    }
    // CRC check (needle.from_bytes): stored raw or legacy transform
    uint32_t stored = get_u32be(b + 16 + size);
    uint32_t actual = sw_crc32c_update(0, (const char*)data, data_size);
    uint32_t rotated = ((actual >> 15) | (actual << 17));
    uint32_t legacy = rotated + 0xA282EAD8u;
    if (stored != actual && stored != legacy) {
        json_response(c, 500, "Internal Server Error",
                      "{\"error\": \"CRC error! Data On Disk Corrupted\"}");
        return true;
    }
    std::string extra = "Accept-Ranges: bytes\r\n";
    {
        char etag[32];
        snprintf(etag, sizeof etag, "ETag: \"%08x\"\r\n", actual);
        extra += etag;
    }
    if (!name.empty()) {
        extra += "Content-Disposition: inline; filename=\"";
        // match urllib.parse.quote: conservative percent-encoding
        for (unsigned char ch : name) {
            if (isalnum(ch) || ch == '_' || ch == '.' || ch == '-' || ch == '~' || ch == '/')
                extra += (char)ch;
            else {
                char buf[4];
                snprintf(buf, sizeof buf, "%%%02X", ch);
                extra += buf;
            }
        }
        extra += "\"\r\n";
    }
    if (flags & 0x01) extra += "Content-Encoding: gzip\r\n";  // IS_COMPRESSED
    std::string ctype = mime.empty() ? "application/octet-stream" : mime;
    // single-range slicing (server/volume.py _do_read semantics; multi-part
    // ranges were already filtered to the proxy by the caller)
    int status = 200;
    const char* out_p = (const char*)data;
    size_t out_n = data_size;
    if (!range.empty()) {
        long long start, end;
        // unintelligible or unsatisfiable specs serve the full entity
        // (volume.py _do_read applies the same rule)
        if (parse_range_spec(range, data_size, &start, &end) == 0) {
            char cr[96];
            snprintf(cr, sizeof cr, "Content-Range: bytes %lld-%lld/%u\r\n",
                     start, end, data_size);
            extra += cr;
            out_p = (const char*)data + start;
            out_n = (size_t)(end - start + 1);
            status = 206;
        }
    }
    if (head) {
        char hint[64];
        snprintf(hint, sizeof hint, "Content-Length-Hint: %zu\r\n", out_n);
        extra += hint;
        append_response(c, status, status == 206 ? "Partial Content" : "OK",
                        ctype, extra, "", 0, false);
    } else if (out_n >= kZeroCopyMin) {
        // zero-copy: the pread blob moves onto the out2 lane; headers +
        // body leave in one writev instead of a second body memcpy
        respond_zc_owned(c, status, status == 206 ? "Partial Content" : "OK",
                         ctype, extra, std::move(blob),
                         (size_t)(out_p - blob.data()), out_n);
    } else {
        append_response(c, status, status == 206 ? "Partial Content" : "OK",
                        ctype, extra, out_p, out_n, false);
    }
    uint64_t served = head ? 0 : (uint64_t)out_n;
    v->m_reads.fetch_add(1, std::memory_order_relaxed);
    v->m_read_bytes.fetch_add(served, std::memory_order_relaxed);
    observe_op(E, c, kOpRead, served);
    E->stats.native_reads++;
    return true;
}

// first file part of a multipart/form-data body (filename= present) —
// mirrors httpd.py Request.multipart_file. Returns false if no file part
// (caller proxies; Python answers exactly as before).
bool multipart_first_file(const std::string& ctype, const char* body,
                          size_t body_len, std::string* filename,
                          std::string* part_type, const char** data,
                          size_t* data_len) {
    size_t bpos = ctype.find("boundary=");
    if (bpos == std::string::npos) return false;
    std::string boundary = ctype.substr(bpos + 9);
    if (!boundary.empty() && boundary[0] == '"') {
        size_t endq = boundary.find('"', 1);
        boundary = boundary.substr(1, endq == std::string::npos
                                          ? std::string::npos : endq - 1);
    } else {
        size_t semi = boundary.find(';');
        if (semi != std::string::npos) boundary = boundary.substr(0, semi);
    }
    if (boundary.empty()) return false;
    std::string delim = "--" + boundary;
    // raw-memory scan: no copy of the (possibly multi-MB) upload body
    const char* end = body + body_len;
    const char* pos = (const char*)memmem(body, body_len, delim.data(),
                                          delim.size());
    while (pos != nullptr) {
        pos += delim.size();
        const char* hdr_end = (const char*)memmem(pos, (size_t)(end - pos),
                                                  "\r\n\r\n", 4);
        if (!hdr_end) break;
        std::string head(pos, (size_t)(hdr_end - pos));
        const char* dstart = hdr_end + 4;
        const char* dend = (const char*)memmem(
            dstart, (size_t)(end - dstart), delim.data(), delim.size());
        if (!dend) break;
        size_t plen = (size_t)(dend - dstart);
        // part data ends before the CRLF preceding the next delimiter
        if (plen >= 2 && dend[-2] == '\r' && dend[-1] == '\n') plen -= 2;
        size_t fpos = head.find("filename=\"");
        if (fpos != std::string::npos) {
            size_t fend = head.find('"', fpos + 10);
            if (fend == std::string::npos) return false;
            *filename = head.substr(fpos + 10, fend - fpos - 10);
            part_type->clear();
            size_t ct = 0;
            // case-insensitive Content-Type scan within the part head
            for (size_t i = 0; i + 13 <= head.size(); i++)
                if (strncasecmp(head.c_str() + i, "content-type:", 13) == 0) {
                    ct = i + 13;
                    break;
                }
            if (ct) {
                size_t eol = head.find('\r', ct);
                if (eol == std::string::npos) eol = head.size();
                while (ct < eol && (head[ct] == ' ' || head[ct] == '\t'))
                    ct++;
                while (eol > ct &&
                       (head[eol - 1] == ' ' || head[eol - 1] == '\t'))
                    eol--;
                *part_type = head.substr(ct, eol - ct);
            }
            *data = dstart;
            *data_len = plen;
            return true;
        }
        pos = dend;
    }
    return false;
}

// ---------------------------------------------------------------------------
// native write / delete
// ---------------------------------------------------------------------------

bool handle_write(Engine* E, Conn* c, std::shared_ptr<Vol>& v, uint64_t key,
                  uint32_t cookie, const char* data, size_t data_len,
                  const std::string& name, const std::string& mime,
                  uint64_t trace_id = 0) {
    if (data_len > 0xFFFFFFFFull) return false;
    // build the v2/v3 record (needle.py to_bytes with data non-empty)
    uint8_t flags = 0x08;  // HAS_LAST_MODIFIED (server always sets it)
    std::string nm = name.substr(0, 255);
    std::string mm = mime;
    if (!nm.empty()) flags |= 0x02;
    if (!mm.empty()) flags |= 0x04;
    int32_t size = 4 + (int32_t)data_len + 1 + 5;
    if (!nm.empty()) size += 1 + (int32_t)nm.size();
    if (!mm.empty()) size += 1 + (int32_t)mm.size();
    int version = v->version;
    int64_t total = actual_size(size, version);
    std::string rec;
    rec.resize(total, 0);
    uint8_t* o = (uint8_t*)&rec[0];
    put_u32be(o, cookie);
    put_u64be(o + 4, key);
    put_u32be(o + 12, (uint32_t)size);
    uint8_t* w = o + 16;
    put_u32be(w, (uint32_t)data_len); w += 4;
    memcpy(w, data, data_len); w += data_len;
    *w++ = flags;
    if (!nm.empty()) { *w++ = (uint8_t)nm.size(); memcpy(w, nm.data(), nm.size()); w += nm.size(); }
    if (!mm.empty()) { *w++ = (uint8_t)mm.size(); memcpy(w, mm.data(), mm.size()); w += mm.size(); }
    uint64_t lm = (uint64_t)time(nullptr);
    for (int i = 4; i >= 0; i--) *w++ = (uint8_t)(lm >> (8 * i));
    uint32_t crc = sw_crc32c_update(0, data, data_len);
    put_u32be(w, crc); w += 4;
    uint64_t ns;
    uint64_t offset;
    {
        std::lock_guard<std::mutex> l(v->append_mu);
        if (v->readonly.load()) return false;  // raced a readonly flip: proxy
        ns = now_ns();
        uint64_t last = v->last_ns.load(std::memory_order_relaxed);
        if (ns <= last) ns = last + 1;
        if (version == 3) { put_u64be(w, ns); }
        offset = v->tail.load(std::memory_order_relaxed);
        if (offset % 8) offset += 8 - offset % 8;
        if (offset + total > (1ull << 35)) return false;  // 4B idx offsets
        ssize_t wr = pwrite(v->dat_fd, rec.data(), total, offset);
        if (wr != total) {
            json_response(c, 500, "Internal Server Error",
                          "{\"error\": \"write failed\"}");
            return true;
        }
        // idx entry: key u64 BE | offset/8 u32 BE | size u32 BE (O_APPEND fd)
        uint8_t ie[16];
        put_u64be(ie, key);
        put_u32be(ie + 8, (uint32_t)(offset / 8));
        put_u32be(ie + 12, (uint32_t)size);
        if (write(v->idx_fd, ie, 16) != 16) {
            json_response(c, 500, "Internal Server Error",
                          "{\"error\": \"idx write failed\"}");
            return true;
        }
        {
            std::unique_lock<std::shared_mutex> ml(v->map_mu);
            v->nmap.put(key, offset, size);
        }
        v->tail.store(offset + total, std::memory_order_relaxed);
        v->last_ns.store(ns, std::memory_order_relaxed);
    }
    E->push_event({v->vid, 0, key, offset, size, 0, ns, trace_id});
    std::string body = "{\"name\": \"";
    json_escape(nm, body);
    char tailbuf[64];
    snprintf(tailbuf, sizeof tailbuf, "\", \"size\": %zu, \"eTag\": \"%08x\"}",
             data_len, crc);
    body += tailbuf;
    json_response(c, 201, "Created", body);
    v->m_writes.fetch_add(1, std::memory_order_relaxed);
    v->m_write_bytes.fetch_add(data_len, std::memory_order_relaxed);
    observe_op(E, c, kOpWrite, data_len);
    E->stats.native_writes++;
    return true;
}

bool handle_delete(Engine* E, Conn* c, std::shared_ptr<Vol>& v, uint64_t key,
                   uint32_t cookie, uint64_t trace_id = 0) {
    // no cookie check on delete — matches storage/volume.py delete_needle
    uint64_t off; int32_t size;
    {
        std::shared_lock<std::shared_mutex> l(v->map_mu);
        if (!v->nmap.get(key, &off, &size) || size <= 0) {
            json_response(c, 202, "Accepted", "{\"size\": 0}");
            return true;
        }
    }
    // tombstone record: empty needle (size=0) + idx entry size=-1
    int version = v->version;
    int32_t zsize = 0;
    int64_t total = actual_size(zsize, version);
    std::string rec;
    rec.resize(total, 0);
    uint8_t* o = (uint8_t*)&rec[0];
    put_u32be(o, cookie);
    put_u64be(o + 4, key);
    put_u32be(o + 12, 0);
    put_u32be(o + 16, 0);  // crc32c of empty = 0
    uint64_t ns, offset;
    int32_t freed = size;
    {
        std::lock_guard<std::mutex> l(v->append_mu);
        if (v->readonly.load()) return false;
        {
            // re-check under the append lock (racing delete/overwrite)
            std::shared_lock<std::shared_mutex> ml(v->map_mu);
            if (!v->nmap.get(key, &off, &freed) || freed <= 0) {
                json_response(c, 202, "Accepted", "{\"size\": 0}");
                return true;
            }
        }
        ns = now_ns();
        uint64_t last = v->last_ns.load(std::memory_order_relaxed);
        if (ns <= last) ns = last + 1;
        if (version == 3) put_u64be(o + 20, ns);
        offset = v->tail.load(std::memory_order_relaxed);
        if (offset % 8) offset += 8 - offset % 8;
        if (pwrite(v->dat_fd, rec.data(), total, offset) != total) {
            json_response(c, 500, "Internal Server Error",
                          "{\"error\": \"write failed\"}");
            return true;
        }
        uint8_t ie[16];
        put_u64be(ie, key);
        put_u32be(ie + 8, (uint32_t)(offset / 8));
        put_u32be(ie + 12, 0xFFFFFFFFu);  // tombstone size -1
        if (write(v->idx_fd, ie, 16) != 16) {
            json_response(c, 500, "Internal Server Error",
                          "{\"error\": \"idx write failed\"}");
            return true;
        }
        {
            std::unique_lock<std::shared_mutex> ml(v->map_mu);
            v->nmap.del(key);
        }
        v->tail.store(offset + total, std::memory_order_relaxed);
        v->last_ns.store(ns, std::memory_order_relaxed);
    }
    E->push_event({v->vid, 1, key, offset, freed, 0, ns, trace_id});
    char body[48];
    snprintf(body, sizeof body, "{\"size\": %d}", freed);
    json_response(c, 202, "Accepted", body);
    v->m_deletes.fetch_add(1, std::memory_order_relaxed);
    observe_op(E, c, kOpDelete, 0);
    E->stats.native_deletes++;
    return true;
}

// ---------------------------------------------------------------------------
// proxy to the Python backend
// ---------------------------------------------------------------------------

int back_recv(BackendConn* b, char* buf, int n) {
    if (b->ssl == nullptr) {
        ssize_t r = recv(b->fd, buf, n, 0);
        if (r > 0) return (int)r;
        if (r == 0) return 0;
        return (errno == EAGAIN || errno == EWOULDBLOCK) ? -1 : -2;
    }
    TlsApi* T = tls_api();
    int r = T->SSL_read(b->ssl, buf, n);
    if (r > 0) return r;
    int e = T->SSL_get_error(b->ssl, r);
    if (e == kSSL_ERROR_WANT_READ) return -1;
    if (e == kSSL_ERROR_WANT_WRITE) return -3;
    return r == 0 ? 0 : -2;
}

int back_send(BackendConn* b, const char* buf, int n) {
    if (b->ssl == nullptr) {
        ssize_t r = send(b->fd, buf, n, MSG_NOSIGNAL);
        if (r >= 0) return (int)r;
        // plain-socket EAGAIN on send = the send buffer is full: resume
        // on WRITABILITY (-3), not readability
        return (errno == EAGAIN || errno == EWOULDBLOCK) ? -3 : -2;
    }
    TlsApi* T = tls_api();
    int r = T->SSL_write(b->ssl, buf, n);
    if (r > 0) return r;
    int e = T->SSL_get_error(b->ssl, r);
    if (e == kSSL_ERROR_WANT_READ) return -1;
    if (e == kSSL_ERROR_WANT_WRITE) return -3;
    return -2;
}

// take a healthy pooled keep-alive conn (fd + optional TLS session) or
// return -1; dead entries (peer closed while idle) are discarded
int pool_take(std::vector<std::pair<int, void*>>& pool, void** ssl_out) {
    while (!pool.empty()) {
        int fd = pool.back().first;
        void* ssl = pool.back().second;
        pool.pop_back();
        char probe;
        ssize_t r = recv(fd, &probe, 1, MSG_PEEK | MSG_DONTWAIT);
        if (r == 0 || (r < 0 && errno != EAGAIN && errno != EWOULDBLOCK)) {
            if (ssl != nullptr) tls_api()->SSL_free(ssl);
            close(fd);
            continue;
        }
        *ssl_out = ssl;
        return fd;
    }
    *ssl_out = nullptr;
    return -1;
}

void back_free_ssl(BackendConn* b) {
    if (b->ssl != nullptr) {
        tls_api()->SSL_free(b->ssl);
        b->ssl = nullptr;
    }
}

int backend_connect(uint32_t ip, int port) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    struct sockaddr_in sa;
    memset(&sa, 0, sizeof sa);
    sa.sin_family = AF_INET;
    sa.sin_port = htons(port);
    sa.sin_addr.s_addr = ip;
    if (connect(fd, (struct sockaddr*)&sa, sizeof sa) != 0) {
        close(fd);
        return -1;
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    set_nonblock(fd);
    return fd;
}

void flush_out(Worker* w, Conn* c);
void process_buffered(Engine* E, Worker* w, Conn* c);
void drain_buffered(Engine* E, Worker* w, Conn* c);

void backend_finish(Worker* w, BackendConn* b, bool reusable) {
    for (size_t i = 0; i < w->pending.size(); i++)
        if (w->pending[i] == b) {
            w->pending[i] = w->pending.back();
            w->pending.pop_back();
            if (b->counted) w->capped_inflight--;
            break;
        }
    if (b->fd >= 0) {
        epoll_ctl(w->epfd, EPOLL_CTL_DEL, b->fd, nullptr);
        auto& pool =
            b->target_ip != 0
                ? w->idle_targets[((uint64_t)b->target_ip << 16) |
                                  (uint16_t)b->target_port]
                : w->idle_backends;
        if (reusable && pool.size() < 8) {
            pool.emplace_back(b->fd, b->ssl);  // TLS session rides along
            b->ssl = nullptr;
        } else {
            back_free_ssl(b);
            close(b->fd);
        }
        b->fd = -1;
    }
    back_free_ssl(b);  // non-pooled leftovers
    w->back_graveyard.push_back(b);
}

// launch (or relaunch, on retry) the upstream request; never blocks
bool backend_launch(Engine* E, Worker* w, BackendConn* b) {
    uint32_t ip = b->target_ip ? b->target_ip : E->backend_ip;
    int port = b->target_ip ? b->target_port : E->backend_port;
    void* ssl = nullptr;
    auto& pool = b->target_ip != 0
                     ? w->idle_targets[((uint64_t)b->target_ip << 16) |
                                       (uint16_t)b->target_port]
                     : w->idle_backends;
    int fd = pool_take(pool, &ssl);
    bool pooled = fd >= 0;
    for (;;) {
        if (fd < 0) {
            fd = backend_connect(ip, port);
            if (fd < 0) return false;
            // upstream hops to non-Python targets speak the cluster's
            // mTLS (a volume engine terminates TLS): attach a CLIENT
            // session presenting this node's cert; the handshake rides
            // the first SSL_write/SSL_read on the nonblocking fd
            if (b->target_ip != 0 && E->tls_client_ctx != nullptr) {
                TlsApi* T = tls_api();
                ssl = T->SSL_new(E->tls_client_ctx);
                if (ssl == nullptr) {
                    close(fd);
                    return false;
                }
                T->SSL_set_fd(ssl, fd);
                T->SSL_set_connect_state(ssl);
            }
        }
        b->fd = fd;
        b->ssl = ssl;
        b->from_pool = pooled;
        b->req_off = 0;
        b->resp.clear();
        b->hdr_end = 0;
        b->body_mode = 0;
        b->started = time(nullptr);
        // optimistic send; leftover bytes flush on the next epoll event
        bool want_write = false, failed = false;
        while (b->req_off < b->req.size()) {
            int n = back_send(b, b->req.data() + b->req_off,
                              (int)std::min(b->req.size() - b->req_off,
                                            (size_t)1 << 20));
            if (n > 0) { b->req_off += n; continue; }
            if (n == -1) break;                       // wait for read
            if (n == -3) { want_write = true; break; }  // wait for write
            failed = true;
            break;
        }
        if (failed) {
            back_free_ssl(b);
            close(fd);
            b->fd = -1;
            fd = -1;
            ssl = nullptr;
            if (pooled) {  // a pooled conn died between probe and send
                pooled = false;  // (TLS close_notify buffered behind the
                continue;        // peek): retry once on a fresh socket
            }
            return false;
        }
        struct epoll_event ev;
        // EPOLLOUT only when the last operation blocked on WRITE: a TLS
        // handshake blocked on READ with unsent bytes must not arm it,
        // or the empty send buffer makes epoll spin at 100% CPU
        ev.events = EPOLLIN | (want_write ? EPOLLOUT : 0);
        b->armed = ev.events;
        ev.data.ptr = b;
        epoll_ctl(w->epfd, EPOLL_CTL_ADD, fd, &ev);
        return true;
    }
}

// Connection is hop-by-hop (RFC 7230 §6.1): forwarding a client's
// "Connection: close" verbatim makes the Python backend close its side
// AFTER responding — without advertising close in the response — so the
// engine pools a socket that is already dying. Enough close-mode clients
// (urllib sends it on every request) turn the whole idle pool into
// corpses, and a proxied request that pops two in a row 502s. Rewrite
// the header to keep-alive on the backend hop; the client-side close is
// the engine's own business.
void rewrite_hop_connection(std::string& req) {
    size_t he = req.find("\r\n\r\n");
    if (he == std::string::npos) return;
    for (size_t pos = req.find("\r\n"); pos < he;
         pos = req.find("\r\n", pos + 2)) {
        size_t ls = pos + 2;
        if (ls + 11 > he) break;
        if (strncasecmp(req.data() + ls, "connection:", 11) != 0) continue;
        size_t le = req.find("\r\n", ls);
        req.replace(ls, le - ls, "Connection: keep-alive");
        return;
    }
}

// bypass_cap: long-poll endpoints (meta subscriptions) park cheaply in a
// Python thread for up to 30s — counting them against the backend cap
// would let a couple of subscribers starve every other request
void proxy_request(Engine* E, Worker* w, Conn* c, const char* req, size_t len,
                   bool bypass_cap = false) {
    auto* b = new BackendConn();
    b->client = c;
    b->req.assign(req, len);
    rewrite_hop_connection(b->req);
    b->started = time(nullptr);
    b->start_ns = mono_ns();
    b->counted = !bypass_cap;
    b->head_request = len >= 5 && memcmp(req, "HEAD ", 5) == 0;
    c->upstream = b;  // halts further request processing on this client
    if (b->counted && w->capped_inflight >= E->max_backend) {
        w->waiting.push_back(b);  // dispatched as in-flight requests finish
        return;
    }
    if (!backend_launch(E, w, b)) {
        c->upstream = nullptr;
        delete b;
        json_response(c, 502, "Bad Gateway",
                      "{\"error\": \"backend unavailable\"}");
        c->want_close = true;
        return;
    }
    if (b->counted) w->capped_inflight++;
    w->pending.push_back(b);
}

// dispatch queued proxied requests into freed backend slots
void drain_waiting(Engine* E, Worker* w) {
    while (!w->waiting.empty() && w->capped_inflight < E->max_backend) {
        BackendConn* b = w->waiting.front();
        w->waiting.pop_front();
        if (b->client == nullptr) {  // client vanished while queued
            w->back_graveyard.push_back(b);
            continue;
        }
        if (!backend_launch(E, w, b)) {
            Conn* c = b->client;
            c->upstream = nullptr;
            json_response(c, 502, "Bad Gateway",
                          "{\"error\": \"backend unavailable\"}");
            c->want_close = true;
            flush_out(w, c);
            w->back_graveyard.push_back(b);
            continue;
        }
        w->capped_inflight++;
        w->pending.push_back(b);
    }
}

void filer_upload_finish(Engine* E, Worker* w, BackendConn* b, bool ok);
void filer_relay_finish(Engine* E, Worker* w, BackendConn* b, bool ok);
void s3_get_finish(Engine* E, Worker* w, BackendConn* b, bool ok);
void s3_put_finish(Engine* E, Worker* w, BackendConn* b, bool ok);
void s3_delete_finish(Engine* E, Worker* w, BackendConn* b, bool ok);

// deliver the completed (or failed) upstream response and resume the
// client's request pipeline; filer-mode conns have their own finishers
void backend_complete(Engine* E, Worker* w, BackendConn* b, bool ok,
                      bool client_keep, bool reusable) {
    if (b->mode == 1) { filer_upload_finish(E, w, b, ok); return; }
    if (b->mode == 2) { filer_relay_finish(E, w, b, ok); return; }
    if (b->mode == 3) { s3_get_finish(E, w, b, ok); return; }
    if (b->mode == 4) { s3_put_finish(E, w, b, ok); return; }
    if (b->mode == 5) { s3_delete_finish(E, w, b, ok); return; }
    Conn* c = b->client;
    if (c != nullptr) {
        c->upstream = nullptr;
        if (ok) {
            c->out += b->resp;
            if (!client_keep) c->want_close = true;
            E->op_stats[kOpProxy].observe(mono_ns() - b->start_ns,
                                          b->resp.size());
            E->stats.proxied++;
        } else {
            json_response(c, 502, "Bad Gateway",
                          "{\"error\": \"backend unavailable\"}");
            c->want_close = true;
        }
    }
    backend_finish(w, b, reusable);
    drain_waiting(E, w);
    if (c != nullptr) {
        drain_buffered(E, w, c);
    }
}

// returns true when the buffered response is complete
bool backend_parse(BackendConn* b) {
    if (b->hdr_end == 0) {
        size_t he = b->resp.find("\r\n\r\n");
        if (he == std::string::npos) return false;
        // interim 1xx responses (100 Continue to a forwarded Expect
        // header) precede the real one: drop and keep parsing
        if (b->resp.compare(0, 9, "HTTP/1.1 ") == 0 && b->resp[9] == '1') {
            b->resp.erase(0, he + 4);
            return backend_parse(b);
        }
        b->hdr_end = he + 4;
        const char* hb = b->resp.data();
        const char* hend = hb + b->hdr_end;
        std::string cl = find_header(hb, hend, "content-length");
        std::string te = find_header(hb, hend, "transfer-encoding");
        std::string ch = find_header(hb, hend, "connection");
        b->backend_close = strcasecmp(ch.c_str(), "close") == 0;
        if (b->head_request) {
            // HEAD responses advertise the entity size but ship no body
            b->body_mode = 1;
            b->body_need = b->hdr_end;
        } else if (!cl.empty()) {
            b->body_mode = 1;
            b->body_need = b->hdr_end + strtoull(cl.c_str(), nullptr, 10);
        } else if (strcasecmp(te.c_str(), "chunked") == 0) {
            b->body_mode = 2;
            b->chunk_pos = b->hdr_end;
        } else {
            b->body_mode = 3;  // close-delimited
        }
    }
    if (b->body_mode == 1) return b->resp.size() >= b->body_need;
    if (b->body_mode == 2) {
        for (;;) {
            size_t le = b->resp.find("\r\n", b->chunk_pos);
            if (le == std::string::npos) return false;
            size_t chunk = strtoull(b->resp.c_str() + b->chunk_pos, nullptr, 16);
            size_t need = le + 2 + chunk + 2;
            if (b->resp.size() < need) return false;
            b->chunk_pos = need;
            if (chunk == 0) return true;
        }
    }
    return false;  // close-delimited: completes on EOF
}

void on_backend_event(Engine* E, Worker* w, BackendConn* b, uint32_t events) {
    bool want_write = false;
    if (b->req_off < b->req.size()) {
        while (b->req_off < b->req.size()) {
            int n = back_send(b, b->req.data() + b->req_off,
                              (int)std::min(b->req.size() - b->req_off,
                                            (size_t)1 << 20));
            if (n > 0) { b->req_off += n; continue; }
            if (n == -1) break;
            if (n == -3) { want_write = true; break; }
            events |= EPOLLERR;
            break;
        }
    }
    bool eof = false, err = (events & EPOLLERR) != 0;
    if (!err) {
        char buf[65536];
        for (;;) {
            int n = back_recv(b, buf, sizeof buf);
            if (n > 0) { b->resp.append(buf, n); continue; }
            if (n == 0) { eof = true; break; }
            if (n == -1) break;
            if (n == -3) { want_write = true; break; }
            err = true;
            break;
        }
    }
    if (!err && !eof) {
        // keep the interest mask exact: EPOLLOUT only while an operation
        // is blocked on WRITE — a stale EPOLLOUT on an idle-writable
        // socket is a level-triggered busy-spin
        uint32_t want = EPOLLIN | (want_write ? EPOLLOUT : 0);
        if (want != b->armed) {
            struct epoll_event ev;
            ev.events = want;
            ev.data.ptr = b;
            epoll_ctl(w->epfd, EPOLL_CTL_MOD, b->fd, &ev);
            b->armed = want;
        }
    }
    if (!err && backend_parse(b)) {
        backend_complete(E, w, b, true, true, !b->backend_close && !eof);
        return;
    }
    if (eof && !err && b->body_mode == 3 && b->hdr_end != 0) {
        // close-delimited response fully read: forward, close client too
        backend_complete(E, w, b, true, false, false);
        return;
    }
    if (err || eof) {
        // nothing usable arrived — relaunch. A POOLED keep-alive socket
        // dying between requests is routine (the peer may close after
        // responding without having advertised Connection: close), and
        // the pool can hold SEVERAL such corpses at once, so pooled
        // deaths retry for as long as the launch keeps drawing from the
        // pool; only a FRESH connection gets exactly one retry before
        // the 502 — that one really means the backend is unavailable.
        if (b->resp.empty() && (b->from_pool || !b->retried)) {
            if (!b->from_pool) b->retried = true;
            epoll_ctl(w->epfd, EPOLL_CTL_DEL, b->fd, nullptr);
            back_free_ssl(b);
            close(b->fd);
            b->fd = -1;
            if (backend_launch(E, w, b)) return;
        }
        backend_complete(E, w, b, false, false, false);
    }
}

// ---------------------------------------------------------------------------
// HS256 write-JWT verification (`weed/security/jwt.go`; Python mirror
// security/jwt.py). The engine only accepts tokens it can fully verify;
// anything else proxies to Python, which produces the exact 401 bodies.
// ---------------------------------------------------------------------------

int b64url_decode(const char* in, size_t n, uint8_t* out, size_t cap) {
    struct Table {
        int8_t t[256];
        Table() {
            memset(t, -1, sizeof t);
            const char* az = "ABCDEFGHIJKLMNOPQRSTUVWXYZ"
                             "abcdefghijklmnopqrstuvwxyz0123456789-_";
            for (int i = 0; i < 64; i++) t[(uint8_t)az[i]] = (int8_t)i;
        }
    };
    static const Table tbl;  // C++11 magic static: thread-safe init
    const int8_t* T = tbl.t;
    uint32_t acc = 0;
    int bits = 0;
    size_t o = 0;
    for (size_t i = 0; i < n; i++) {
        int8_t v = T[(uint8_t)in[i]];
        if (v < 0) return -1;
        acc = (acc << 6) | (uint32_t)v;
        bits += 6;
        if (bits >= 8) {
            bits -= 8;
            if (o >= cap) return -1;
            out[o++] = (uint8_t)(acc >> bits);
        }
    }
    return (int)o;
}

// verify "BEARER <jwt>" against `key` and the request's base fid
// ("<vid>,<hexkey+cookie>" with any _delta stripped). Wildcard fid claims
// ("") are accepted, as the filer's tokens use them. Shared by the write
// path (jwt.signing.key) and the read path (jwt.signing.read.key) —
// `weed/server/volume_server_handlers.go:33-75` checks both the same way.
bool jwt_fid_ok(const std::string& key, const std::string& auth,
                const char* fid_path, size_t fid_len) {
    if (key.empty()) return true;
    if (strncasecmp(auth.c_str(), "BEARER ", 7) != 0) return false;
    const char* tok = auth.c_str() + 7;
    const char* dot1 = strchr(tok, '.');
    if (!dot1) return false;
    const char* dot2 = strchr(dot1 + 1, '.');
    if (!dot2) return false;
    // signature check first (constant-time-ish compare)
    uint8_t want[32], got[40];
    sw_hmac_sha256((const uint8_t*)key.data(), key.size(),
                   (const uint8_t*)tok, (size_t)(dot2 - tok), want);
    int got_n = b64url_decode(dot2 + 1, strlen(dot2 + 1), got, sizeof got);
    if (got_n != 32) return false;
    uint8_t diff = 0;
    for (int i = 0; i < 32; i++) diff |= want[i] ^ got[i];
    if (diff) return false;
    // claims: {"fid":"...","exp":N} (our own compact encoder)
    uint8_t payload[512];
    int pn = b64url_decode(dot1 + 1, (size_t)(dot2 - dot1 - 1), payload,
                           sizeof payload - 1);
    if (pn < 0) return false;
    payload[pn] = 0;
    const char* ps = (const char*)payload;
    const char* fp = strstr(ps, "\"fid\":");
    if (!fp) return false;
    fp += 6;
    while (*fp == ' ') fp++;
    if (*fp != '"') return false;
    fp++;
    const char* fe = strchr(fp, '"');
    if (!fe) return false;
    size_t claim_len = (size_t)(fe - fp);
    if (claim_len != 0) {  // empty claim = wildcard token
        // base fid: strip any _delta suffix from the request's fid part
        size_t base_len = fid_len;
        for (size_t i = 0; i < fid_len; i++)
            if (fid_path[i] == '_' || fid_path[i] == '.') { base_len = i; break; }
        if (claim_len != base_len || memcmp(fp, fid_path, base_len) != 0)
            return false;
    }
    const char* ep = strstr(ps, "\"exp\":");
    if (ep) {
        long long exp = atoll(ep + 6);
        if (exp > 0 && (long long)time(nullptr) > exp) return false;
    }
    return true;
}

// ---------------------------------------------------------------------------
// native /dir/assign (master fastlane)
// ---------------------------------------------------------------------------

// fid key+cookie hex per storage/file_id.py: the 8-byte key's leading zero
// BYTES are stripped (whole bytes, so always an even digit count), then the
// 8 cookie digits always follow
void format_fid_hex(uint64_t key, uint32_t cookie, char* out) {
    static const char* hexd = "0123456789abcdef";
    int lead = 0;
    while (lead < 8 && ((key >> (56 - 8 * lead)) & 0xFF) == 0) lead++;
    char* p = out;
    for (int i = lead; i < 8; i++) {
        uint8_t b = (key >> (56 - 8 * i)) & 0xFF;
        *p++ = hexd[b >> 4];
        *p++ = hexd[b & 0xF];
    }
    for (int i = 7; i >= 0; i--) *p++ = hexd[(cookie >> (4 * i)) & 0xF];
    *p = 0;
}

bool handle_assign(Engine* E, Conn* c, const char* query, size_t qlen) {
    std::shared_ptr<AssignProfile> ap;
    {
        std::shared_lock<std::shared_mutex> l(E->assign_mu);
        auto it = E->assigns.find(std::string(query, qlen));
        if (it == E->assigns.end()) return false;
        ap = it->second;
    }
    uint64_t key = ap->next_key.fetch_add(1, std::memory_order_relaxed);
    if (key >= ap->end_key) return false;  // lease spent: Python re-leases
    size_t vi = ap->rr.fetch_add(1, std::memory_order_relaxed) % ap->vids.size();
    // xorshift cookie seeded per call from the key + clock
    static thread_local uint64_t rng = 0x9e3779b97f4a7c15ull ^ now_ns();
    rng ^= rng << 13; rng ^= rng >> 7; rng ^= rng << 17;
    uint32_t cookie = (uint32_t)(rng ^ (rng >> 32));
    char hex[32];
    format_fid_hex(key, cookie, hex);
    char fid[48];
    int fl = snprintf(fid, sizeof fid, "%u,%s", ap->vids[vi], hex);
    std::string body = "{\"fid\": \"";
    body.append(fid, fl);
    body += "\", ";
    body += ap->tails[vi];
    json_response(c, 200, "OK", body);
    observe_op(E, c, kOpAssign, 0);
    E->stats.native_assigns++;
    return true;
}

// ---------------------------------------------------------------------------
// filer-mode plumbing
// ---------------------------------------------------------------------------

// entry frame, shared by the journal (crash replay) and the Python drain:
// u32 frame_len | u8 kind (0 chunk, 1 inline) | u8 pad[3] | u64 size |
// u64 mtime_sec | char md5_hex[32] | u16 path_len | u16 fid_len |
// u16 mime_len | u16 content_len | path | fid | mime | content
std::string filer_frame(uint8_t kind, uint64_t size, uint64_t mtime,
                        const char md5_hex[32], const std::string& path,
                        const std::string& fid, const std::string& mime,
                        const char* content, size_t content_len) {
    uint32_t total = 4 + 4 + 8 + 8 + 32 + 8 + (uint32_t)path.size() +
                     (uint32_t)fid.size() + (uint32_t)mime.size() +
                     (uint32_t)content_len;
    std::string f;
    f.reserve(total);
    auto le32 = [&](uint32_t v) { f.append((const char*)&v, 4); };
    auto le64 = [&](uint64_t v) { f.append((const char*)&v, 8); };
    auto le16 = [&](uint16_t v) { f.append((const char*)&v, 2); };
    le32(total);
    f.push_back((char)kind);
    f.append(3, '\0');
    le64(size);
    le64(mtime);
    f.append(md5_hex, 32);
    le16((uint16_t)path.size());
    le16((uint16_t)fid.size());
    le16((uint16_t)mime.size());
    le16((uint16_t)content_len);
    f += path;
    f += fid;
    f += mime;
    if (content_len) f.append(content, content_len);
    return f;
}

void md5_hex_of(const char* data, size_t len, char out_hex[33]) {
    unsigned char digest[16];
    const unsigned char* ptr = (const unsigned char*)data;
    size_t l = len;
    sw_md5_batch_var(&ptr, &l, 1, digest);
    static const char* hexd = "0123456789abcdef";
    for (int i = 0; i < 16; i++) {
        out_hex[2 * i] = hexd[digest[i] >> 4];
        out_hex[2 * i + 1] = hexd[digest[i] & 0xF];
    }
    out_hex[32] = 0;
}

// journal-before-ack (the filer analog of the volume engine writing .idx
// before acking): append the frame, then queue it for the Python drain.
// Returns false when the event backlog says Python stalled — the caller
// must proxy instead of acking writes nobody will ever apply.
bool filer_commit(Engine* E, const std::string& frame) {
    std::lock_guard<std::mutex> l(E->filer_mu);
    if (E->filer_events.size() >= 100000) return false;  // backpressure
    if (E->filer_journal_fd >= 0) {
        off_t before = lseek(E->filer_journal_fd, 0, SEEK_END);
        ssize_t wr = write(E->filer_journal_fd, frame.data(), frame.size());
        if (wr != (ssize_t)frame.size()) {
            // a torn frame mid-file would desynchronize crash replay once
            // later frames append after it — cut it off before proxying
            if (before >= 0) {
                if (ftruncate(E->filer_journal_fd, before) != 0) {
                    // can't restore a clean tail: stop journaling (and
                    // with it all native writes) rather than corrupt it
                    close(E->filer_journal_fd);
                    E->filer_journal_fd = -1;
                    E->filer_mode.store(false, std::memory_order_release);
                }
            }
            return false;
        }
    }
    E->filer_events.push_back(frame);
    E->filer_events_bytes += frame.size();
    return true;
}

void fcache_put(Engine* E, const std::string& path,
                std::shared_ptr<FilerCacheEnt> ent) {
    std::unique_lock<std::shared_mutex> l(E->fcache_mu);
    auto old = E->fcache.find(path);
    bool carried = false;
    if (old != E->fcache.end() && !old->second->inline_data.empty()) {
        if (ent->inline_data.empty() && old->second->md5_hex == ent->md5_hex) {
            // same entity (md5 = full-body hash), chunk-backed re-put —
            // a meta-log replay or Python-read cache refresh must not
            // DEMOTE a promoted object back to relaying (slow boxes hit
            // this every refresh; the promotion looked permanently hot
            // but quietly died). Carry the inline body over; its bytes
            // are already accounted in fcache_inline_bytes.
            ent->inline_data = old->second->inline_data;
            carried = true;
        } else {
            E->fcache_inline_bytes -= old->second->inline_data.size();
        }
    }
    if (!ent->inline_data.empty() && !carried)
        E->fcache_inline_bytes += ent->inline_data.size();
    ent->seq = ++E->fcache_seq;
    E->fcache_fifo.emplace_back(path, ent->seq);
    E->fcache[path] = std::move(ent);
    // FIFO eviction, bounding inline payload bytes AND total entry count
    // (chunk-backed entries cost a few hundred bytes each and a busy
    // filer touches millions of paths). A re-put leaves its old FIFO
    // slot behind as a stale (path, seq) pair — the seq check makes
    // popping it a no-op, and the queue itself is compacted whenever it
    // outgrows the live set so overwrite churn cannot leak queue slots.
    int budget = 64;  // amortized: each put cleans at most 64 queue slots
    while (!E->fcache_fifo.empty() && budget-- > 0) {
        bool over_bytes = E->fcache_inline_bytes > (128u << 20);
        bool over_count = E->fcache.size() > 1000000;
        bool over_fifo =
            E->fcache_fifo.size() > 2 * E->fcache.size() + 1024;
        if (!over_bytes && !over_count && !over_fifo) break;
        auto victim = std::move(E->fcache_fifo.front());
        E->fcache_fifo.pop_front();
        auto it = E->fcache.find(victim.first);
        if (it != E->fcache.end() && it->second->seq == victim.second) {
            if (over_bytes || over_count) {
                if (!it->second->inline_data.empty())
                    E->fcache_inline_bytes -= it->second->inline_data.size();
                E->fcache.erase(it);
            } else {
                // compaction only: rotate the live head to the back so the
                // stale slots behind it become poppable
                E->fcache_fifo.push_back(std::move(victim));
            }
        }
    }
}

// compare-and-promote: attach inline bytes to an existing chunk-backed
// entry, atomically against the meta-log subscriber's puts/dels — the
// check and the insert share one unique lock, so a racing overwrite's
// fresh entry (different md5) can never be clobbered by stale bytes
void fcache_promote(Engine* E, const std::string& path,
                    const std::string& md5_hex, const char* body,
                    size_t blen) {
    std::unique_lock<std::shared_mutex> l(E->fcache_mu);
    auto it = E->fcache.find(path);
    if (it == E->fcache.end()) return;
    auto& old = it->second;
    if (old->md5_hex != md5_hex || !old->inline_data.empty()) return;
    auto ent = std::make_shared<FilerCacheEnt>(*old);
    ent->inline_data.assign(body, blen);
    E->fcache_inline_bytes += blen;
    ent->seq = ++E->fcache_seq;
    E->fcache_fifo.emplace_back(path, ent->seq);
    it->second = std::move(ent);
    // budget enforcement happens on the next fcache_put pass; one
    // 64KB-capped promotion cannot meaningfully overshoot 128MB
}

void fcache_del(Engine* E, const std::string& path) {
    std::unique_lock<std::shared_mutex> l(E->fcache_mu);
    if (path.empty()) {
        E->fcache.clear();
        E->fcache_fifo.clear();
        E->fcache_inline_bytes = 0;
        return;
    }
    auto it = E->fcache.find(path);
    if (it != E->fcache.end()) {
        if (!it->second->inline_data.empty())
            E->fcache_inline_bytes -= it->second->inline_data.size();
        E->fcache.erase(it);
    }
}

// serve a cached INLINE entry straight from memory: ETag/304, single
// Range, Content-Type — the same surface filer.py _do_read produces
void filer_serve_inline(Engine* E, Conn* c,
                        const std::shared_ptr<FilerCacheEnt>& ent,
                        const char* req, size_t hdr_len, bool head) {
    const char* he = req + hdr_len;
    std::string etag = "\"" + ent->md5_hex + "\"";
    std::string extra = "Accept-Ranges: bytes\r\nETag: " + etag + "\r\n";
    {
        char lm[64];
        time_t t = (time_t)ent->mtime;
        struct tm g;
        gmtime_r(&t, &g);
        strftime(lm, sizeof lm, "Last-Modified: %a, %d %b %Y %H:%M:%S GMT\r\n",
                 &g);
        extra += lm;
    }
    std::string inm = find_header(req, he, "if-none-match");
    std::string ctype =
        ent->mime.empty() ? "application/octet-stream" : ent->mime;
    if (!inm.empty() && inm == etag) {
        append_response(c, 304, "Not Modified", ctype, extra, "", 0, false);
        observe_op(E, c, kOpRead, 0);
        E->stats.native_reads++;
        front_native_inc(E, kFrRead);
        return;
    }
    const std::string& data = ent->inline_data;
    int status = 200;
    size_t off = 0, n = data.size();
    std::string range = find_header(req, he, "range");
    if (!range.empty() && range.find(',') == std::string::npos) {
        long long start, end;
        int rr = parse_range_spec(range, data.size(), &start, &end);
        if (rr == 1) {  // valid syntax, unsatisfiable: filer.py sends 416
            char cr[64];
            snprintf(cr, sizeof cr, "Content-Range: bytes */%zu\r\n",
                     data.size());
            append_response(c, 416, "Range Not Satisfiable", "", cr, "", 0,
                            false);
            observe_op(E, c, kOpRead, 0);
            E->stats.native_reads++;
            front_native_inc(E, kFrRead);
            return;
        }
        if (rr == 0) {
            char cr[96];
            snprintf(cr, sizeof cr, "Content-Range: bytes %lld-%lld/%zu\r\n",
                     start, end, data.size());
            extra += cr;
            off = (size_t)start;
            n = (size_t)(end - start + 1);
            status = 206;
        }
    }
    if (head) {
        char cl[64];
        snprintf(cl, sizeof cl, "X-File-Size: %zu\r\n", data.size());
        extra += cl;
    }
    if (!head && n >= kZeroCopyMin) {
        // serve straight out of the cache entry: the shared_ptr pins the
        // bytes for the write's lifetime, no copy into the conn buffer
        respond_zc_pinned(
            c, status, status == 206 ? "Partial Content" : "OK", ctype,
            extra,
            std::shared_ptr<const void>(ent, (const void*)ent.get()),
            data.data() + off, n);
    } else {
        append_response(c, status, status == 206 ? "Partial Content" : "OK",
                        ctype, extra, data.data() + off, n, head);
    }
    observe_op(E, c, kOpRead, head ? 0 : n);
    E->stats.native_reads++;
    front_native_inc(E, kFrRead);
}

// finish a native filer write once the entry is journaled: cache + respond
void filer_write_ack(Engine* E, Conn* c, const std::string& path,
                     uint64_t size, const char* md5_hex) {
    std::string base = path.substr(path.rfind('/') + 1);
    std::string body = "{\"name\": \"";
    json_escape(base, body);
    char tail[96];
    snprintf(tail, sizeof tail, "\", \"size\": %llu, \"md5\": \"%.32s\"}",
             (unsigned long long)size, md5_hex);
    body += tail;
    json_response(c, 201, "Created", body);
    observe_op(E, c, kOpWrite, size);
    E->stats.native_writes++;
    front_native_inc(E, kFrWrite);
}

// mode-1 completion: the volume server answered the chunk upload
void filer_upload_finish(Engine* E, Worker* w, BackendConn* b, bool ok) {
    Conn* c = b->client;
    int status = 0;
    if (ok && b->resp.size() > 12 && memcmp(b->resp.data(), "HTTP/1.1 ", 9) == 0)
        status = atoi(b->resp.c_str() + 9);
    bool good = ok && status == 201;
    uint64_t mtime = (uint64_t)time(nullptr);
    if (good) {
        std::string frame =
            filer_frame(0, b->f_size, mtime, b->f_md5hex.c_str(), b->f_path,
                        b->f_fid, b->f_mime, nullptr, 0);
        good = filer_commit(E, frame);
    }
    if (good) {
        auto ent = std::make_shared<FilerCacheEnt>();
        ent->ip = b->target_ip;
        ent->port = b->target_port;
        ent->fid = b->f_fid;
        ent->mime = b->f_mime;
        ent->md5_hex = b->f_md5hex;
        ent->size = b->f_size;
        ent->mtime = mtime;
        fcache_put(E, b->f_path, std::move(ent));
    }
    if (c != nullptr && !good) {
        // the upload failed (volume down / moved / DELETED under the
        // lease — volume.delete.empty on a not-yet-written volume does
        // exactly this): drop the lease THAT MINTED THIS FID so Python
        // re-leases against live topology (the rest of the pool keeps
        // serving), and replay THIS request through the Python path so
        // the client still gets its write
        drop_filer_lease(E, b->f_lease);
        front_fb_inc(E, kFrWrite, kFbUpstream);
        Conn* cc = c;
        std::string original = std::move(b->client_req);
        backend_finish(w, b, false);
        drain_waiting(E, w);
        cc->upstream = nullptr;
        proxy_request(E, w, cc, original.data(), original.size(), false);
        flush_out(w, cc);
        return;
    }
    if (c != nullptr) {
        c->upstream = nullptr;
        filer_write_ack(E, c, b->f_path, b->f_size, b->f_md5hex.c_str());
    }
    backend_finish(w, b, ok && !b->backend_close);
    if (c != nullptr) {
        drain_buffered(E, w, c);
    }
}

void proxy_request(Engine* E, Worker* w, Conn* c, const char* req, size_t len,
                   bool bypass_cap);

// mode-2 completion: relay the volume response, ETag rewritten to the
// entry's md5 (what the Python filer serves); on any failure drop the
// cache entry and replay the original request through the Python path
void filer_relay_finish(Engine* E, Worker* w, BackendConn* b, bool ok) {
    Conn* c = b->client;
    int status = 0;
    if (ok && b->resp.size() > 12 && memcmp(b->resp.data(), "HTTP/1.1 ", 9) == 0)
        status = atoi(b->resp.c_str() + 9);
    if (ok && (status == 200 || status == 206 || status == 304) &&
        b->hdr_end != 0) {
        if (c != nullptr) {
            c->upstream = nullptr;
            // rewrite the ETag header inside the buffered head
            std::string head = b->resp.substr(0, b->hdr_end);
            size_t p = 0;
            bool replaced = false;
            while (p < head.size()) {
                size_t eol = head.find("\r\n", p);
                if (eol == std::string::npos) break;
                if (strncasecmp(head.c_str() + p, "etag:", 5) == 0) {
                    head.replace(p, eol - p, "ETag: \"" + b->f_md5hex + "\"");
                    replaced = true;
                    break;
                }
                p = eol + 2;
            }
            if (!replaced)
                head.insert(head.size() - 2,
                            "ETag: \"" + b->f_md5hex + "\"\r\n");
            if (b->f_mtime) {  // filer.py also serves Last-Modified
                char lm[64];
                time_t t = (time_t)b->f_mtime;
                struct tm g;
                gmtime_r(&t, &g);
                strftime(lm, sizeof lm,
                         "Last-Modified: %a, %d %b %Y %H:%M:%S GMT\r\n", &g);
                head.insert(head.size() - 2, lm);
            }
            size_t blen = b->resp.size() - b->hdr_end;
            observe_op(E, c, kOpRead, blen);
            E->stats.native_reads++;
            front_native_inc(E, kFrRead);
            // promote small hot objects: a FULL-entity, length-framed
            // relay body moves into the inline cache (same 128MB budget +
            // FIFO eviction, same meta-log invalidation), so repeat reads
            // skip the volume hop entirely. body_mode==1 only — chunked/
            // close-delimited responses carry framing or may be truncated.
            if (status == 200 && b->body_mode == 1 && blen > 0 &&
                blen <= 65536)
                fcache_promote(E, b->f_path, b->f_md5hex,
                               b->resp.data() + b->hdr_end, blen);
            c->out += head;
            if (blen >= kZeroCopyMin && c->out2_len == 0) {
                // relay body rides the zero-copy lane: the upstream
                // response buffer moves as-is, out2_data skips its head
                c->out2 = std::move(b->resp);
                c->out2_data = c->out2.data() + b->hdr_end;
                c->out2_len = blen;
                c->out2_off = 0;
            } else {
                c->out.append(b->resp, b->hdr_end, blen);
            }
        }
        backend_finish(w, b, !b->backend_close);
        drain_waiting(E, w);
        if (c != nullptr) {
            drain_buffered(E, w, c);
        }
        return;
    }
    // miss/moved/error: forget the location and let Python serve it
    fcache_del(E, b->f_path);
    front_fb_inc(E, kFrRead, kFbUpstream);
    std::string original = std::move(b->client_req);
    backend_finish(w, b, false);
    drain_waiting(E, w);
    if (c != nullptr) {
        c->upstream = nullptr;
        proxy_request(E, w, c, original.data(), original.size(), false);
        flush_out(w, c);
    }
}

// native filer write: inline entries commit synchronously; chunk-backed
// entries mint a leased fid and upload to the volume engine async.
// Returns false when any gate says the Python path must take it.
bool handle_filer_write(Engine* E, Worker* w, Conn* c,
                        const std::string& path, const char* req,
                        size_t hdr_len, const char* body, size_t body_len) {
    const char* he = req + hdr_len;
    std::string ctype = find_header(req, he, "content-type");
    const char* data = body;
    size_t dlen = body_len;
    std::string mime = ctype;
    auto fb = [&](int reason) {  // typed fallback: metric, then proxy
        front_fb_inc(E, kFrWrite, reason);
        return false;
    };
    if (ctype.rfind("multipart/form-data", 0) == 0) {
        std::string pn, pt;
        if (!multipart_first_file(ctype, body, body_len, &pn, &pt, &data,
                                  &dlen))
            return fb(kFbBodyShape);
        mime = pt;
    } else if (ctype.rfind("multipart/", 0) == 0) {
        return fb(kFbBodyShape);
    }
    if (mime == "application/x-www-form-urlencoded") mime.clear();
    if (mime.size() >= 250 || mime.find_first_of("\r\n") != std::string::npos)
        return fb(kFbBodyShape);
    if (path.size() > 60000) return fb(kFbOther);  // frame lengths are u16
    // the /etc/ config area (filer.conf, IAM, dedup index) must be
    // visible the moment the write acks — config consumers read through
    // Python, so skip the drain-delayed native path entirely. The system
    // meta-log tree emits NO meta events (filer_notify skips it), so a
    // natively-cached entry there could never be invalidated — skip too.
    if (path.compare(0, 5, "/etc/") == 0) return fb(kFbSystemPath);
    if (path.compare(0, 16, "/topics/.system/") == 0) return fb(kFbSystemPath);
    {
        // paths under an fs.configure rule prefix carry storage options
        // (collection/replication/ttl/read-only) that only the Python
        // write pipeline resolves
        std::shared_lock<std::shared_mutex> rl(E->frules_mu);
        for (const auto& pre : E->frule_prefixes)
            if (path.compare(0, pre.size(), pre) == 0)
                return fb(kFbSystemPath);
    }
    if (dlen <= E->filer_inline_limit) {
        // small-content inlining (filer.py SMALL_CONTENT_LIMIT): no volume
        // hop at all — journal, cache, ack
        char md5hex[33];
        md5_hex_of(data, dlen, md5hex);
        uint64_t mtime = (uint64_t)time(nullptr);
        std::string frame =
            filer_frame(1, dlen, mtime, md5hex, path, "", mime, data, dlen);
        if (!filer_commit(E, frame)) return fb(kFbBackpressure);
        auto ent = std::make_shared<FilerCacheEnt>();
        ent->inline_data.assign(data, dlen);
        ent->mime = mime;
        ent->md5_hex = md5hex;
        ent->size = dlen;
        ent->mtime = mtime;
        fcache_put(E, path, std::move(ent));
        filer_write_ack(E, c, path, dlen, md5hex);
        return true;
    }
    if (dlen > E->filer_chunk_limit)
        return fb(kFbTooLarge);  // multi-chunk: Python
    if (E->filer_compress) {
        // the Python pipeline compresses by mime AND by extension
        // (util/compression.py is_compressable_file_type); anything its
        // heuristic might gzip must take the Python path
        if (!mime.empty() && mime != "application/octet-stream")
            return fb(kFbBodyShape);
        size_t dot = path.rfind('.');
        size_t slash = path.rfind('/');
        if (dot != std::string::npos &&
            (slash == std::string::npos || dot > slash)) {
            std::string ext = path.substr(dot);
            for (auto& ch : ext) ch = (char)tolower((unsigned char)ch);
            static const char* kTextExt[] = {
                ".csv", ".txt", ".json", ".xml", ".html", ".htm", ".css",
                ".js", ".log", ".md", ".yaml", ".yml", ".toml", ".svg",
                ".conf", ".ini", ".py", ".go", ".java", ".c", ".cpp", ".h",
                ".rs", ".ts", ".sql", ".sh", ".pdf",
            };
            for (const char* t : kTextExt)
                if (ext == t) return fb(kFbBodyShape);
        }
    }
    uint64_t key = 0;
    int lease_reason = kFbNoLease;
    std::shared_ptr<FilerLease> L = take_filer_lease(E, &key, &lease_reason);
    if (!L) return fb(lease_reason);
    char hex[32];
    format_fid_hex(key, L->cookie, hex);
    char fid[48];
    int fl = snprintf(fid, sizeof fid, "%u,%s", L->vid, hex);
    char md5hex[33];
    md5_hex_of(data, dlen, md5hex);
    auto* b = new BackendConn();
    b->client = c;
    b->mode = 1;
    b->target_ip = L->vol_ip;
    b->target_port = L->vol_port;
    b->f_lease = L;  // a failed upload drops exactly this lease
    // kept for the failure path: a dead/moved/deleted lease volume makes
    // the finisher replay this request through the Python backend
    b->client_req.assign(req, hdr_len + body_len);
    b->f_path = path;
    b->f_fid.assign(fid, fl);
    b->f_mime = mime;
    b->f_md5hex = md5hex;
    b->f_size = dlen;
    b->f_trace = parse_trace_id(find_header(req, he, "x-sw-trace-id"));
    b->started = time(nullptr);
    std::string& r = b->req;
    r.reserve(dlen + 256 + path.size());
    r = "POST /";
    r.append(fid, fl);
    r += " HTTP/1.1\r\nHost: v\r\n";
    std::string base = path.substr(path.rfind('/') + 1);
    if (!base.empty() && base.size() < 250 &&
        base.find_first_of("\r\n") == std::string::npos) {
        r += "X-File-Name: ";
        r += base;
        r += "\r\n";
    }
    if (!mime.empty()) {
        r += "Content-Type: ";
        r += mime;
        r += "\r\n";
    }
    if (!L->auth.empty()) {
        r += "Authorization: ";
        r += L->auth;
        r += "\r\n";
    }
    if (b->f_trace) {
        // the volume engine stamps this id on its append event, so the
        // drain-synthesized span joins the caller's trace end to end
        char th[48];
        snprintf(th, sizeof th, "X-Sw-Trace-Id: %016llx\r\n",
                 (unsigned long long)b->f_trace);
        r += th;
    }
    char cl[48];
    snprintf(cl, sizeof cl, "Content-Length: %zu\r\n\r\n", dlen);
    r += cl;
    r.append(data, dlen);
    c->upstream = b;
    if (!backend_launch(E, w, b)) {
        c->upstream = nullptr;
        delete b;
        return fb(kFbUpstream);  // volume unreachable: Python's surface
    }
    w->pending.push_back(b);
    return true;
}

// native filer read of a chunk-backed entry: relay to the volume engine
void filer_relay_launch(Engine* E, Worker* w, Conn* c,
                        const std::shared_ptr<FilerCacheEnt>& ent,
                        const std::string& path, const char* req,
                        size_t req_len, size_t hdr_len) {
    auto* b = new BackendConn();
    b->client = c;
    b->mode = 2;
    b->target_ip = ent->ip;
    b->target_port = ent->port;
    b->f_path = path;
    b->f_md5hex = ent->md5_hex;
    b->f_mtime = ent->mtime;
    b->client_req.assign(req, req_len);
    b->started = time(nullptr);
    std::string& r = b->req;
    r = "GET /" + ent->fid + " HTTP/1.1\r\nHost: v\r\n";
    const char* he = req + hdr_len;
    std::string range = find_header(req, he, "range");
    if (!range.empty()) {
        r += "Range: ";
        r += range;
        r += "\r\n";
    }
    {
        std::shared_lock<std::shared_mutex> l(E->flease_mu);
        if (!E->filer_read_auth.empty()) {
            r += "Authorization: ";
            r += E->filer_read_auth;
            r += "\r\n";
        }
    }
    r += "\r\n";
    c->upstream = b;
    if (!backend_launch(E, w, b)) {
        c->upstream = nullptr;
        delete b;
        front_fb_inc(E, kFrRead, kFbUpstream);
        proxy_request(E, w, c, req, req_len, false);
        return;
    }
    w->pending.push_back(b);
}

// native filer DELETE: known (cached) file entries tombstone + journal +
// ack without a Python hop — the same journal-before-ack contract as the
// write path, with frame kind 2 applied as Filer.delete_entry by the
// drain. Returns false when the Python path must take it (with the typed
// fallback reason counted).
bool handle_filer_delete(Engine* E, Conn* c, const std::string& path) {
    auto fb = [&](int reason) {
        front_fb_inc(E, kFrDelete, reason);
        return false;
    };
    // config-area deletes must be visible to Python consumers the moment
    // they ack; fs.configure prefixes may be read_only (Python enforces)
    if (path.compare(0, 5, "/etc/") == 0) return fb(kFbSystemPath);
    if (path.compare(0, 16, "/topics/.system/") == 0)
        return fb(kFbSystemPath);
    if (path.size() > 60000) return fb(kFbOther);
    {
        std::shared_lock<std::shared_mutex> rl(E->frules_mu);
        for (const auto& pre : E->frule_prefixes)
            if (path.compare(0, pre.size(), pre) == 0)
                return fb(kFbSystemPath);
    }
    std::shared_ptr<FilerCacheEnt> ent;
    {
        std::shared_lock<std::shared_mutex> l(E->fcache_mu);
        auto it = E->fcache.find(path);
        if (it != E->fcache.end()) ent = it->second;
    }
    // only entries the cache KNOWS to be plain files delete natively —
    // a miss could be a directory (recursive semantics) or a missing
    // path (409 surface); Python answers those exactly
    if (ent == nullptr) return fb(kFbCacheMiss);
    if (ent->tombstone) {
        // double-delete before the drain lands: Python would 409 "not
        // found" — route it there for the exact surface
        return fb(kFbCacheMiss);
    }
    static const char kZeroMd5[33] = "00000000000000000000000000000000";
    uint64_t mtime = (uint64_t)time(nullptr);
    std::string frame =
        filer_frame(2, ent->size, mtime, kZeroMd5, path, "", "", nullptr, 0);
    if (!filer_commit(E, frame)) return fb(kFbBackpressure);
    auto tomb = std::make_shared<FilerCacheEnt>();
    tomb->tombstone = true;
    tomb->size = ent->size;
    tomb->mtime = mtime;
    fcache_put(E, path, std::move(tomb));
    append_response(c, 204, "No Content", "", "", "", 0, false);
    observe_op(E, c, kOpDelete, 0);
    E->stats.native_deletes++;
    front_native_inc(E, kFrDelete);
    return true;
}

// ---------------------------------------------------------------------------
// s3 front mode: protocol-translating relays onto the FILER's engine front
// door. The gateway's Python surface keeps everything stateful (sigv4,
// policies, versioning, ACLs, CORS, x-amz metadata); the engine serves the
// gated plain-object subset — which is the bench/production hot path — by
// rewriting /bucket/key <-> /buckets/bucket/key and translating status
// codes, so object bytes never cross the GIL.
// ---------------------------------------------------------------------------

void xml_escape(const std::string& s, std::string& out) {
    for (char ch : s) {
        switch (ch) {
            case '<': out += "&lt;"; break;
            case '>': out += "&gt;"; break;
            case '&': out += "&amp;"; break;
            default: out += ch;
        }
    }
}

// same XML error surface s3_server.py error_response produces
void s3_error_response(Conn* c, int status, const char* reason,
                       const char* code, const char* msg,
                       const std::string& resource) {
    std::string body =
        "<?xml version=\"1.0\" encoding=\"UTF-8\"?><Error><Code>";
    body += code;
    body += "</Code><Message>";
    body += msg;
    body += "</Message><Resource>";
    xml_escape(resource, body);
    body += "</Resource></Error>";
    append_response(c, status, reason, "application/xml", "", body.data(),
                    body.size(), false);
}

// replay the original client request through the Python S3 surface (the
// filer answered something the translation table doesn't cover)
void s3_replay_python(Engine* E, Worker* w, BackendConn* b, int frop) {
    front_fb_inc(E, frop, kFbUpstream);
    Conn* c = b->client;
    std::string original = std::move(b->client_req);
    backend_finish(w, b, false);
    drain_waiting(E, w);
    if (c != nullptr) {
        c->upstream = nullptr;
        proxy_request(E, w, c, original.data(), original.size(), false);
        flush_out(w, c);
    }
}

void s3_finish_common(Engine* E, Worker* w, BackendConn* b, Conn* c) {
    backend_finish(w, b, !b->backend_close);
    drain_waiting(E, w);
    if (c != nullptr) {
        drain_buffered(E, w, c);
    }
}

// mode 3: object GET — the filer front's response is already S3-shaped
// (ETag = "md5", Content-Type, Accept-Ranges, Last-Modified); forward its
// head verbatim and the body zero-copy
void s3_get_finish(Engine* E, Worker* w, BackendConn* b, bool ok) {
    Conn* c = b->client;
    int status = 0;
    if (ok && b->resp.size() > 12 &&
        memcmp(b->resp.data(), "HTTP/1.1 ", 9) == 0)
        status = atoi(b->resp.c_str() + 9);
    if (ok && b->hdr_end != 0 &&
        (status == 200 || status == 206 || status == 304)) {
        if (c != nullptr) {
            c->upstream = nullptr;
            size_t blen = b->resp.size() - b->hdr_end;
            observe_op(E, c, kOpRead, blen);
            E->stats.native_reads++;
            front_native_inc(E, kFrRead);
            if (blen >= kZeroCopyMin && c->out2_len == 0) {
                c->out.append(b->resp, 0, b->hdr_end);
                c->out2 = std::move(b->resp);
                c->out2_data = c->out2.data() + b->hdr_end;
                c->out2_len = blen;
                c->out2_off = 0;
            } else {
                c->out += b->resp;
            }
        }
        s3_finish_common(E, w, b, c);
        return;
    }
    if (ok && b->hdr_end != 0 && status == 404) {
        if (c != nullptr) {
            c->upstream = nullptr;
            s3_error_response(c, 404, "Not Found", "NoSuchKey",
                              "no such key", b->f_path);
            observe_op(E, c, kOpRead, 0);
            E->stats.native_reads++;
            front_native_inc(E, kFrRead);
        }
        s3_finish_common(E, w, b, c);
        return;
    }
    s3_replay_python(E, w, b, kFrRead);
}

// mode 4: object/part PUT — filer 201 becomes S3 200 with the ETag the
// engine already computed (md5 of the body, exactly hashlib.md5 in
// _put_object/_upload_part)
void s3_put_finish(Engine* E, Worker* w, BackendConn* b, bool ok) {
    Conn* c = b->client;
    int status = 0;
    if (ok && b->resp.size() > 12 &&
        memcmp(b->resp.data(), "HTTP/1.1 ", 9) == 0)
        status = atoi(b->resp.c_str() + 9);
    if (ok && b->hdr_end != 0 && status == 201) {
        if (c != nullptr) {
            c->upstream = nullptr;
            std::string extra = "ETag: \"" + b->f_md5hex + "\"\r\n";
            append_response(c, 200, "OK", "", extra, "", 0, false);
            observe_op(E, c, kOpWrite, b->f_size);
            E->stats.native_writes++;
            front_native_inc(E, kFrWrite);
        }
        s3_finish_common(E, w, b, c);
        return;
    }
    s3_replay_python(E, w, b, kFrWrite);
}

// mode 5: object DELETE — S3 answers 204 whether or not the key existed,
// so success and 404 translate to 204. A 409 is NOT accepted: the filer
// answers 409 both for a missing entry AND for a non-empty directory, and
// the Python path deletes directories recursively (fc.delete
// recursive=True) — acking 409 as 204 would silently no-op a subtree
// delete the slow path executes. Python resolves both 409 flavors to the
// right outcome, so replay instead.
void s3_delete_finish(Engine* E, Worker* w, BackendConn* b, bool ok) {
    Conn* c = b->client;
    int status = 0;
    if (ok && b->resp.size() > 12 &&
        memcmp(b->resp.data(), "HTTP/1.1 ", 9) == 0)
        status = atoi(b->resp.c_str() + 9);
    if (ok && b->hdr_end != 0 && (status < 300 || status == 404)) {
        if (c != nullptr) {
            c->upstream = nullptr;
            append_response(c, 204, "No Content", "", "", "", 0, false);
            observe_op(E, c, kOpDelete, 0);
            E->stats.native_deletes++;
            front_native_inc(E, kFrDelete);
        }
        s3_finish_common(E, w, b, c);
        return;
    }
    s3_replay_python(E, w, b, kFrDelete);
}

// gate + launch for one s3-front request; returns false when the request
// must take the Python path (typed fallback reason counted by the caller
// only for transport failures — gates count their own)
bool handle_s3_front(Engine* E, Worker* w, Conn* c, const std::string& method,
                     const char* req, size_t req_len, size_t hdr_len,
                     const char* body, size_t body_len, const char* path,
                     const char* fid_end, const char* qmark,
                     const char* path_end) {
    const char* he = req + hdr_len;
    int frop = method == "GET" ? kFrRead
               : method == "DELETE" ? kFrDelete
                                    : kFrWrite;
    auto fb = [&](int reason) {
        front_fb_inc(E, frop, reason);
        return false;
    };
    // /<bucket>/<key...>: both parts non-empty, canonical (the Python side
    // normalizes/unquotes anything else). Bucket-level requests are
    // namespace ops, not object traffic — they proxy without front-door
    // accounting.
    std::string pstr(path, fid_end - path);
    if (pstr.size() < 4 || pstr[0] != '/') return false;
    size_t slash = pstr.find('/', 1);
    if (slash == std::string::npos || slash + 1 >= pstr.size())
        return false;  // bucket-level op
    if (pstr.back() == '/') return fb(kFbOther);  // directory-style key
    if (pstr.find('%') != std::string::npos ||
        pstr.find("//") != std::string::npos ||
        pstr.find("/./") != std::string::npos ||
        pstr.find("/../") != std::string::npos)
        return fb(kFbOther);
    std::string bucket = pstr.substr(1, slash - 1);
    if (bucket == "." || bucket.find('.') == 0) return fb(kFbOther);
    // signed requests need sigv4 (Python); Origin-carrying ones need the
    // bucket's CORS decoration; x-amz-* semantics (meta, copy, streaming
    // bodies, tagging, acl) all live in the Python handlers
    if (!find_header(req, he, "authorization").empty()) return fb(kFbAuth);
    if (!find_header(req, he, "origin").empty()) return fb(kFbOther);
    {
        const char* p = req;
        while (p < he) {
            const char* eol = (const char*)memchr(p, '\n', he - p);
            if (!eol) break;
            if (eol - p >= 6 && strncasecmp(p, "x-amz-", 6) == 0 &&
                strncasecmp(p, "x-amz-date:", 11) != 0 &&
                strncasecmp(p, "x-amz-content-sha256:", 21) != 0)
                return fb(kFbBodyShape);
            p = eol + 1;
        }
        // streaming-framed bodies need Python's deframer
        if (find_header(req, he, "x-amz-content-sha256")
                .rfind("STREAMING-", 0) == 0)
            return fb(kFbBodyShape);
        // multipart/form-data bodies are browser POST-policy territory
        if (find_header(req, he, "content-type").rfind("multipart/", 0) == 0)
            return fb(kFbBodyShape);
    }
    // query: only the multipart part-upload shape is served natively
    std::string up_path;  // filer-side target path
    if (qmark != nullptr) {
        if (method != "PUT") return fb(kFbQuery);
        std::string q(qmark + 1, path_end - qmark - 1);
        long part_num = -1;
        std::string upload_id;
        size_t pos = 0;
        bool clean = true;
        while (pos < q.size()) {
            size_t amp = q.find('&', pos);
            if (amp == std::string::npos) amp = q.size();
            std::string kv = q.substr(pos, amp - pos);
            if (kv.rfind("partNumber=", 0) == 0) {
                const char* v = kv.c_str() + 11;
                char* endp = nullptr;
                part_num = strtol(v, &endp, 10);
                if (endp == v || *endp != 0) clean = false;
            } else if (kv.rfind("uploadId=", 0) == 0) {
                upload_id = kv.substr(9);
            } else {
                clean = false;
            }
            pos = amp + 1;
        }
        if (!clean || part_num < 1 || part_num > 10000 || upload_id.empty()
            || upload_id.find_first_not_of(
                   "0123456789abcdefABCDEF") != std::string::npos)
            return fb(kFbQuery);
        {
            std::shared_lock<std::shared_mutex> l(E->s3_mu);
            if (E->s3_uploads.find(bucket + "/" + upload_id) ==
                E->s3_uploads.end())
                return fb(kFbBucketState);  // unknown upload: NoSuchUpload
        }
        char part[16];
        snprintf(part, sizeof part, "%05ld.part", part_num);
        up_path = "/buckets/" + bucket + "/.uploads/" + upload_id + "/" +
                  part;
    }
    // bucket gate: Python installs flags only for buckets whose state the
    // native path can honor (exists, open IAM, no policy/versioning/
    // read-only/meta history) and re-validates them continuously
    int need = frop == kFrRead ? kS3Read
               : frop == kFrWrite ? kS3Write
                                  : kS3Delete;
    {
        std::shared_lock<std::shared_mutex> l(E->s3_mu);
        auto it = E->s3_buckets.find(bucket);
        if (it == E->s3_buckets.end() || (it->second & need) == 0)
            return fb(kFbBucketState);
    }
    if (up_path.empty()) up_path = "/buckets" + pstr;

    auto* b = new BackendConn();
    b->client = c;
    b->target_ip = E->s3_filer_ip;
    b->target_port = E->s3_filer_port;
    b->client_req.assign(req, req_len);
    b->f_path = pstr;
    b->started = time(nullptr);
    std::string& r = b->req;
    if (frop == kFrRead) {
        b->mode = 3;
        r = "GET " + up_path + " HTTP/1.1\r\nHost: f\r\nX-Sw-S3: 1\r\n";
        std::string range = find_header(req, he, "range");
        if (range.find(',') != std::string::npos) {
            delete b;
            return fb(kFbBodyShape);  // multi-range: Python's surface
        }
        if (!range.empty()) r += "Range: " + range + "\r\n";
        std::string inm = find_header(req, he, "if-none-match");
        if (!inm.empty()) r += "If-None-Match: " + inm + "\r\n";
        r += "\r\n";
    } else if (frop == kFrWrite) {
        b->mode = 4;
        char md5hex[33];
        md5_hex_of(body, body_len, md5hex);
        b->f_md5hex = md5hex;
        b->f_size = body_len;
        r.reserve(body_len + 256);
        r = "PUT " + up_path + " HTTP/1.1\r\nHost: f\r\nX-Sw-S3: 1\r\n";
        std::string ctype = find_header(req, he, "content-type");
        if (!ctype.empty() && ctype.size() < 250 &&
            ctype.find_first_of("\r\n") == std::string::npos)
            r += "Content-Type: " + ctype + "\r\n";
        char cl[48];
        snprintf(cl, sizeof cl, "Content-Length: %zu\r\n\r\n", body_len);
        r += cl;
        r.append(body, body_len);
    } else {
        b->mode = 5;
        r = "DELETE " + up_path +
            " HTTP/1.1\r\nHost: f\r\nX-Sw-S3: 1\r\n\r\n";
    }
    c->upstream = b;
    if (!backend_launch(E, w, b)) {
        c->upstream = nullptr;
        delete b;
        return fb(kFbUpstream);  // filer unreachable: Python's surface
    }
    w->pending.push_back(b);
    return true;
}

// ---------------------------------------------------------------------------
// request dispatch
// ---------------------------------------------------------------------------

// handles one complete buffered request [req, req+req_len) whose headers end
// at hdr_len; body follows. Returns nothing — always produces output bytes.
void dispatch(Engine* E, Worker* w, Conn* c, const char* req, size_t req_len,
              size_t hdr_len, const char* body, size_t body_len) {
    E->stats.requests++;
    c->req_start_ns = mono_ns();
    if (!c->cn_ok) {
        // CA-valid client cert with a disallowed CommonName: same per-request
        // 403 surface the Python gate produces (httpd.py _dispatch)
        json_response(c, 403, "Forbidden",
                      "{\"error\": \"client certificate CN not allowed\"}");
        return;
    }
    const char* line_end = (const char*)memchr(req, '\r', hdr_len);
    if (!line_end) { c->want_close = true; return; }
    const char* sp1 = (const char*)memchr(req, ' ', line_end - req);
    if (!sp1) { c->want_close = true; return; }
    const char* sp2 = (const char*)memchr(sp1 + 1, ' ', line_end - sp1 - 1);
    if (!sp2) { c->want_close = true; return; }
    std::string method(req, sp1 - req);
    const char* path = sp1 + 1;
    const char* path_end = sp2;
    const char* qmark = (const char*)memchr(path, '?', path_end - path);
    const char* fid_end = qmark ? qmark : path_end;
    bool has_query = qmark != nullptr;
    const char* he = req + hdr_len;

    if (method == "GET" && (size_t)(fid_end - path) == 11 &&
        memcmp(path, "/dir/assign", 11) == 0) {
        const char* q = has_query ? qmark + 1 : "";
        size_t qlen = has_query ? (size_t)(path_end - qmark - 1) : 0;
        if (handle_assign(E, c, q, qlen)) return;
        proxy_request(E, w, c, req, req_len);  // miss/spent: Python (re)installs
        return;
    }

    // long-poll surfaces: filer meta subscriptions and any wait= query
    bool bypass_cap = false;
    if ((size_t)(fid_end - path) >= 10 && memcmp(path, "/__meta__/", 10) == 0)
        bypass_cap = true;
    else if (has_query) {
        size_t qn = (size_t)(path_end - qmark - 1);
        const char* q = qmark + 1;
        for (size_t i = 0; i + 5 <= qn; i++)
            if (memcmp(q + i, "wait=", 5) == 0 &&
                (i == 0 || q[i - 1] == '&')) {
                bypass_cap = true;
                break;
            }
    }

    // filer mode: serve the path namespace natively where the cache/lease
    // allow; every gate failure counts a typed fallback reason and falls
    // through to the Python proxy below. Percent-escapes and dot-segments
    // would need Python's normalize(); such paths (rare) always proxy so
    // cache keys stay canonical. Directory listings (trailing /) are
    // namespace ops, not chunk traffic — excluded from the accounting.
    if (E->filer_mode.load(std::memory_order_relaxed) && path < fid_end &&
        path[0] == '/' && fid_end[-1] != '/' &&
        !((size_t)(fid_end - path) >= 3 && memcmp(path, "/__", 3) == 0) &&
        (method == "GET" || method == "HEAD" || method == "POST" ||
         method == "PUT" || method == "DELETE")) {
        int frop = (method == "GET" || method == "HEAD") ? kFrRead
                   : method == "DELETE"                  ? kFrDelete
                                                         : kFrWrite;
        std::string pstr(path, fid_end - path);
        bool canonical = pstr.find('%') == std::string::npos &&
                         pstr.find("//") == std::string::npos &&
                         pstr.find("/./") == std::string::npos &&
                         pstr.find("/../") == std::string::npos;
        if (has_query) {
            front_fb_inc(E, frop, kFbQuery);
        } else if (!canonical) {
            front_fb_inc(E, frop, kFbOther);
        } else if (frop == kFrRead) {
            std::shared_ptr<FilerCacheEnt> ent;
            {
                std::shared_lock<std::shared_mutex> l(E->fcache_mu);
                auto it = E->fcache.find(pstr);
                if (it != E->fcache.end()) ent = it->second;
            }
            if (ent == nullptr) {
                front_fb_inc(E, frop, kFbCacheMiss);
            } else if (ent->tombstone) {
                // natively-acked DELETE whose drain hasn't landed yet:
                // read-your-deletes must hold on every engine core, so
                // the tombstone answers 404 instead of proxying into the
                // still-stale Python store
                append_response(c, 404, "Not Found", "", "", "", 0, false);
                observe_op(E, c, kOpRead, 0);
                E->stats.native_reads++;
                front_native_inc(E, kFrRead);
                return;
            } else {
                if (!ent->inline_data.empty()) {
                    filer_serve_inline(E, c, ent, req, hdr_len,
                                       method == "HEAD");
                    return;
                }
                std::string range = find_header(req, he, "range");
                bool multi = range.find(',') != std::string::npos;
                std::string inm = find_header(req, he, "if-none-match");
                if (!inm.empty() && inm == "\"" + ent->md5_hex + "\"") {
                    append_response(c, 304, "Not Modified", "",
                                    "ETag: " + inm + "\r\n", "", 0, false);
                    observe_op(E, c, kOpRead, 0);
                    E->stats.native_reads++;
                    front_native_inc(E, kFrRead);
                    return;
                }
                if (!range.empty() && !multi) {
                    // unsatisfiable ranges 416 here (filer.py semantics);
                    // the volume engine would serve the full entity and
                    // the answer must not depend on cache state
                    long long rs, re2;
                    if (parse_range_spec(range, ent->size, &rs, &re2) == 1) {
                        char cr[64];
                        snprintf(cr, sizeof cr,
                                 "Content-Range: bytes */%llu\r\n",
                                 (unsigned long long)ent->size);
                        append_response(c, 416, "Range Not Satisfiable", "",
                                        cr, "", 0, false);
                        observe_op(E, c, kOpRead, 0);
                        E->stats.native_reads++;
                        front_native_inc(E, kFrRead);
                        return;
                    }
                }
                if (method == "GET" && !multi) {
                    filer_relay_launch(E, w, c, ent, pstr, req, req_len,
                                       hdr_len);
                    return;
                }
                front_fb_inc(E, frop, kFbBodyShape);  // HEAD/multi-range
            }
        } else if (frop == kFrWrite) {
            if (handle_filer_write(E, w, c, pstr, req, hdr_len, body,
                                   body_len))
                return;
            // handle_filer_write counted its own fallback reason
        } else if (handle_filer_delete(E, c, pstr)) {
            return;
        }
    }

    // s3 front mode: gated object GET/PUT/DELETE relays to the filer
    // engine; everything else (bucket ops, auth'd/versioned/meta'd
    // requests) proxies to the Python S3 surface below
    if (E->s3_mode.load(std::memory_order_relaxed) &&
        (method == "GET" || method == "PUT" || method == "DELETE")) {
        if (handle_s3_front(E, w, c, method, req, req_len, hdr_len, body,
                            body_len, path, fid_end, qmark, path_end))
            return;
    }

    uint32_t vid; uint64_t key; uint32_t cookie;
    bool is_fid = path < fid_end && path[0] == '/' &&
                  parse_fid(path + 1, fid_end, &vid, &key, &cookie);
    if (is_fid) {
        auto v = E->vol(vid);
        if (method == "GET" || method == "HEAD") {
            std::string range = find_header(req, he, "range");
            bool multi = range.find(',') != std::string::npos;
            // secure_reads with a key: verify the read JWT natively so
            // hardened clusters keep the native plane; a missing/invalid
            // token proxies to Python for its exact 401 body. ?jwt= query
            // tokens also proxy (has_query), header tokens stay native.
            bool read_ok = !E->secure_reads;
            if (!read_ok && !E->jwt_read_key.empty())
                read_ok = jwt_fid_ok(E->jwt_read_key,
                                     find_header(req, he, "authorization"),
                                     path + 1,
                                     (size_t)(fid_end - path - 1));
            if (v && !has_query && !multi && read_ok) {
                if (handle_read(E, c, v, key, cookie, method == "HEAD",
                                range))
                    return;
            }
            proxy_request(E, w, c, req, req_len, bypass_cap);
            return;
        }
        if (method == "POST" || method == "PUT") {
            // cheap gates first: a request the proxy will take anyway
            // must not pay body parsing
            bool exists = false;
            if (v) {
                uint64_t off_; int32_t size_;
                std::shared_lock<std::shared_mutex> l(v->map_mu);
                exists = v->nmap.get(key, &off_, &size_) && size_ > 0;
            }
            bool jwt_ok = true;
            if (!E->jwt_write_key.empty())
                jwt_ok = jwt_fid_ok(E->jwt_write_key,
                                    find_header(req, he, "authorization"),
                                      path + 1, (size_t)(fid_end - path - 1));
            bool gates_ok = v && !has_query && !exists && jwt_ok &&
                            !E->secure_writes && !v->readonly.load() &&
                            !v->forward_writes.load();
            if (!gates_ok) {
                proxy_request(E, w, c, req, req_len, bypass_cap);
                return;
            }
            std::string ctype = find_header(req, he, "content-type");
            std::string fname = find_header(req, he, "x-file-name");
            const char* wdata = body;
            size_t wlen = body_len;
            std::string mime = ctype;
            bool is_multipart = ctype.rfind("multipart/form-data", 0) == 0;
            bool unsupported =
                !is_multipart && ctype.rfind("multipart/", 0) == 0;
            if (is_multipart) {
                // curl -F / browser-form uploads: extract the file part
                // natively (the reference's own clients upload this way)
                std::string part_name, part_type;
                if (multipart_first_file(ctype, body, body_len, &part_name,
                                         &part_type, &wdata, &wlen)) {
                    fname = part_name;
                    mime = part_type;
                } else {
                    unsupported = true;  // no file part: Python's error path
                }
            } else {
                // header-mime branch only (volume.py _do_write): form and
                // json defaults are transport noise, not the blob's type
                if (mime == "application/json" ||
                    mime == "application/x-www-form-urlencoded")
                    mime.clear();
            }
            bool jpg = false;
            {
                std::string lower = fname;
                for (auto& ch : lower) ch = tolower(ch);
                if (lower.size() >= 4 &&
                    (lower.rfind(".jpg") == lower.size() - 4 ||
                     (lower.size() >= 5 && lower.rfind(".jpeg") == lower.size() - 5)))
                    jpg = true;
                if (mime == "image/jpeg") jpg = true;
            }
            if (!unsupported && !jpg) {
                if (mime == "application/octet-stream" || mime.size() >= 256)
                    mime.clear();  // common needle-set rule (both branches)
                if (handle_write(E, c, v, key, cookie, wdata, wlen, fname,
                                 mime,
                                 parse_trace_id(
                                     find_header(req, he, "x-sw-trace-id"))))
                    return;
            }
            proxy_request(E, w, c, req, req_len, bypass_cap);
            return;
        }
        if (method == "DELETE") {
            bool jwt_ok = true;
            if (!E->jwt_write_key.empty())
                jwt_ok = jwt_fid_ok(E->jwt_write_key,
                                    find_header(req, he, "authorization"),
                                      path + 1, (size_t)(fid_end - path - 1));
            if (v && !has_query && jwt_ok && !E->secure_writes &&
                !v->readonly.load() && !v->forward_writes.load()) {
                if (handle_delete(E, c, v, key, cookie,
                                  parse_trace_id(find_header(
                                      req, he, "x-sw-trace-id"))))
                    return;
            }
            proxy_request(E, w, c, req, req_len, bypass_cap);
            return;
        }
    }
    proxy_request(E, w, c, req, req_len, bypass_cap);
}

// ---------------------------------------------------------------------------
// event loop
// ---------------------------------------------------------------------------

// closes the socket and queues the Conn for deferred deletion — other
// epoll events in the same wait batch may still point at it, so the object
// must stay alive until the next loop pass
void close_conn(Worker* w, Conn* c) {
    if (c->fd >= 0) {
        if (c->upstream != nullptr) {
            // orphan the in-flight (or still-queued) proxy; it completes
            // into the void and its backend conn is not reused
            c->upstream->client = nullptr;
            c->upstream = nullptr;
        }
        if (c->ssl != nullptr) {
            TlsApi* T = tls_api();
            T->SSL_shutdown(c->ssl);  // best-effort close_notify
            T->SSL_free(c->ssl);
            c->ssl = nullptr;
        }
        epoll_ctl(w->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
        close(c->fd);
        c->fd = -1;
        std::lock_guard<std::mutex> l(w->conns_mu);
        for (size_t i = 0; i < w->conns.size(); i++)
            if (w->conns[i] == c) {
                w->conns[i] = w->conns.back();
                w->conns.pop_back();
                break;
            }
        w->graveyard.push_back(c);
    }
}

void flush_out(Worker* w, Conn* c) {
    // two output lanes: `out` (headers + small bodies, always first) and
    // the zero-copy body channel out2. Plaintext sockets push both with a
    // single sendmsg (writev) so a native read costs one syscall and zero
    // body memcpys; TLS writes them sequentially through SSL_write.
    for (;;) {
        bool have_hdr = c->out_off < c->out.size();
        bool have_body = c->out2_off < c->out2_len;
        if (!have_hdr && !have_body) break;
        if (have_hdr && have_body && c->ssl == nullptr) {
            struct iovec iov[2];
            iov[0].iov_base = (void*)(c->out.data() + c->out_off);
            iov[0].iov_len = c->out.size() - c->out_off;
            iov[1].iov_base = (void*)(c->out2_data + c->out2_off);
            iov[1].iov_len = c->out2_len - c->out2_off;
            struct msghdr mh;
            memset(&mh, 0, sizeof mh);
            mh.msg_iov = iov;
            mh.msg_iovlen = 2;
            ssize_t n = sendmsg(c->fd, &mh, MSG_NOSIGNAL);
            if (n < 0) {
                if (errno == EAGAIN || errno == EWOULDBLOCK) {
                    struct epoll_event ev;
                    ev.events = EPOLLIN | EPOLLOUT;
                    ev.data.ptr = c;
                    epoll_ctl(w->epfd, EPOLL_CTL_MOD, c->fd, &ev);
                    return;
                }
                close_conn(w, c);
                return;
            }
            size_t hn = std::min((size_t)n, iov[0].iov_len);
            c->out_off += hn;
            c->out2_off += (size_t)n - hn;
            continue;
        }
        const char* p;
        size_t left;
        if (have_hdr) {
            p = c->out.data() + c->out_off;
            left = c->out.size() - c->out_off;
        } else {
            p = c->out2_data + c->out2_off;
            left = c->out2_len - c->out2_off;
        }
        int n = conn_write(c, p, (int)std::min(left, (size_t)1 << 20));
        if (n > 0) {
            if (have_hdr) c->out_off += n; else c->out2_off += n;
            continue;
        }
        if (n == -1) {
            struct epoll_event ev;
            ev.events = EPOLLIN | EPOLLOUT;
            ev.data.ptr = c;
            epoll_ctl(w->epfd, EPOLL_CTL_MOD, c->fd, &ev);
            return;
        }
        close_conn(w, c);
        return;
    }
    c->out.clear();
    c->out_off = 0;
    std::string().swap(c->out2);  // release, don't retain multi-MB bodies
    c->out2_pin.reset();
    c->out2_data = nullptr;
    c->out2_len = c->out2_off = 0;
    if (c->want_close) { close_conn(w, c); return; }
    struct epoll_event ev;
    ev.events = EPOLLIN;
    ev.data.ptr = c;
    epoll_ctl(w->epfd, EPOLL_CTL_MOD, c->fd, &ev);
}

// zero-copy responders: headers build into c->out, the body parks on the
// out2 channel (flush_out sends both with one writev). Worth the lane
// juggling only for large bodies — small ones append_response directly.
void respond_zc_head(Conn* c, int status, const char* reason,
                     const std::string& ctype, const std::string& extra,
                     size_t body_len) {
    char hdr[512];
    int hn = snprintf(hdr, sizeof hdr,
                      "HTTP/1.1 %d %s\r\nContent-Length: %zu\r\n", status,
                      reason, body_len);
    c->out.append(hdr, hn);
    if (!ctype.empty()) {
        c->out += "Content-Type: ";
        c->out += ctype;
        c->out += "\r\n";
    }
    c->out += extra;
    c->out += "\r\n";
}

void respond_zc_owned(Conn* c, int status, const char* reason,
                      const std::string& ctype, const std::string& extra,
                      std::string&& body, size_t off, size_t n) {
    respond_zc_head(c, status, reason, ctype, extra, n);
    c->out2 = std::move(body);
    c->out2_data = c->out2.data() + off;
    c->out2_len = n;
    c->out2_off = 0;
}

void respond_zc_pinned(Conn* c, int status, const char* reason,
                       const std::string& ctype, const std::string& extra,
                       std::shared_ptr<const void> pin, const char* data,
                       size_t n) {
    respond_zc_head(c, status, reason, ctype, extra, n);
    c->out2_pin = std::move(pin);
    c->out2_data = data;
    c->out2_len = n;
    c->out2_off = 0;
}

// A chunked request body (curl -T -, streaming clients) carries no
// Content-Length; decode it and rebuild the request with one so both the
// native handlers and the Python backend (which only frames by length)
// can serve it. Returns 1 when a rebuilt request replaced c->in's head,
// 0 when more bytes are needed, -1 on a framing error.
int dechunk_request(Conn* c, size_t hdr_len) {
    // resume from the prior scan position: re-walking every chunk per
    // read event would be O(n^2) on large streamed uploads
    size_t pos = c->chunk_scan ? c->chunk_scan : hdr_len;
    for (;;) {
        size_t le = c->in.find("\r\n", pos);
        if (le == std::string::npos) { c->chunk_scan = pos; return 0; }
        if (!isxdigit((unsigned char)c->in[pos])) return -1;  // malformed
        size_t chunk = strtoull(c->in.c_str() + pos, nullptr, 16);
        size_t data_at = le + 2;
        if (chunk == 0) {
            // optional trailers end with a blank line
            size_t fin = c->in.find("\r\n\r\n", le);
            size_t end;
            if (c->in.compare(le, 4, "\r\n\r\n") == 0) end = le + 4;
            else if (fin != std::string::npos) end = fin + 4;
            else { c->chunk_scan = pos; return 0; }
            // rebuild: headers minus Transfer-Encoding, plus Content-Length
            std::string head(c->in, 0, hdr_len - 2);  // keep one CRLF off
            std::string rebuilt;
            size_t line = 0;
            while (line < head.size()) {
                size_t eol = head.find("\r\n", line);
                if (eol == std::string::npos) eol = head.size();
                // drop TE and any client Content-Length: keeping the
                // latter would leave two conflicting lengths in the
                // rebuilt request (smuggling/desync vector)
                if (strncasecmp(head.c_str() + line, "transfer-encoding:",
                                18) != 0 &&
                    strncasecmp(head.c_str() + line, "content-length:",
                                15) != 0)
                    rebuilt.append(head, line, eol + 2 - line);
                line = eol + 2;
            }
            char clh[48];
            snprintf(clh, sizeof clh, "Content-Length: %zu\r\n\r\n",
                     c->chunk_body.size());
            rebuilt += clh;
            rebuilt += c->chunk_body;
            c->in.replace(0, end, rebuilt);
            c->chunk_scan = 0;
            c->chunk_body.clear();
            return 1;
        }
        if (chunk > (1ull << 31)) return -1;
        if (c->in.size() < data_at + chunk + 2) { c->chunk_scan = pos; return 0; }
        c->chunk_body.append(c->in, data_at, chunk);
        pos = data_at + chunk + 2;
        if (c->chunk_body.size() > (1ull << 31)) return -1;
    }
}

// drain complete buffered requests; stops while a proxied request is in
// flight (responses must stay ordered per connection) or while a
// zero-copy body occupies the out2 lane (a later response appended to
// `out` would overtake it on the wire)
void process_buffered(Engine* E, Worker* w, Conn* c) {
    while (c->upstream == nullptr && !c->want_close && c->out2_len == 0) {
        size_t hdr_end = c->in.find("\r\n\r\n");
        if (hdr_end == std::string::npos) {
            if (c->in.size() > (1u << 20)) close_conn(w, c);
            return;
        }
        size_t hdr_len = hdr_end + 4;
        // clients streaming a body often wait for 100 Continue first
        if (!c->sent_continue) {
            std::string expect = find_header(
                c->in.data(), c->in.data() + hdr_len, "expect");
            if (strncasecmp(expect.c_str(), "100-", 4) == 0) {
                c->sent_continue = true;
                c->out += "HTTP/1.1 100 Continue\r\n\r\n";
                flush_out(w, c);
                if (c->fd < 0) return;
            }
        }
        std::string te = find_header(c->in.data(), c->in.data() + hdr_len,
                                     "transfer-encoding");
        if (strcasecmp(te.c_str(), "chunked") == 0) {
            int rc = dechunk_request(c, hdr_len);
            if (rc == 0) return;          // need more chunks
            if (rc < 0) { close_conn(w, c); return; }
            continue;  // re-parse the rebuilt, length-framed request
        }
        std::string cl = find_header(c->in.data(), c->in.data() + hdr_len,
                                     "content-length");
        size_t body_len = cl.empty() ? 0 : strtoull(cl.c_str(), nullptr, 10);
        if (body_len > (1ull << 31)) { close_conn(w, c); return; }
        if (c->in.size() < hdr_len + body_len) return;  // need more body
        size_t req_len = hdr_len + body_len;
        dispatch(E, w, c, c->in.data(), req_len, hdr_len,
                 c->in.data() + hdr_len, body_len);
        c->in.erase(0, req_len);
        c->sent_continue = false;
    }
}

// serve every request already buffered in c->in, interleaving flushes:
// a zero-copy response parks process_buffered until its out2 body lane
// clears, and after a backend completion no further read event will
// arrive to resume the pipeline — a single process_buffered+flush_out
// pass would leave an already-buffered pipelined request stalled until
// the idle sweep. Loops until blocked (partial flush, upstream hop,
// close) or c->in stops shrinking.
void drain_buffered(Engine* E, Worker* w, Conn* c) {
    for (;;) {
        // flush FIRST: when a backend completion parks its body on out2
        // before calling here, process_buffered is gated until the lane
        // clears — flushing last would read "no input consumed" as done
        // and strand the buffered request
        flush_out(w, c);
        if (c->fd < 0 || c->upstream != nullptr || c->want_close ||
            c->out_off < c->out.size() || c->out2_len != 0 || c->in.empty())
            return;
        size_t before = c->in.size();
        process_buffered(E, w, c);
        if (c->fd < 0) return;
        if (c->in.size() == before && c->out_off >= c->out.size() &&
            c->out2_len == 0)
            return;  // no progress and nothing new to flush
    }
}

// drive a pending TLS handshake; afterwards either tls_hs==2 (established,
// CN checked) or the conn is closed or still handshaking (tls_hs==1)
void tls_handshake_step(Engine* E, Worker* w, Conn* c) {
    TlsApi* T = tls_api();
    int r = T->SSL_do_handshake(c->ssl);
    if (r == 1) {
        c->tls_hs = 2;
        if (!E->allowed_cns.empty()) {
            // per-request 403 on CN mismatch (same surface the Python gate
            // produces) — the handshake itself already proved CA validity
            c->cn_ok = false;
            void* cert = T->SSL_get1_peer_certificate(c->ssl);
            if (cert != nullptr) {
                char cn[256] = {0};
                void* name = T->X509_get_subject_name(cert);
                if (name != nullptr &&
                    T->X509_NAME_get_text_by_NID(name, kNID_commonName, cn,
                                                 sizeof cn) > 0) {
                    for (const auto& pat : E->allowed_cns)
                        if (glob_match(pat.c_str(), cn)) {
                            c->cn_ok = true;
                            break;
                        }
                }
                T->X509_free(cert);
            }
        }
        struct epoll_event ev;
        ev.events = EPOLLIN;
        ev.data.ptr = c;
        epoll_ctl(w->epfd, EPOLL_CTL_MOD, c->fd, &ev);
        return;
    }
    int e = T->SSL_get_error(c->ssl, r);
    if (e == kSSL_ERROR_WANT_READ || e == kSSL_ERROR_WANT_WRITE) {
        struct epoll_event ev;
        ev.events = e == kSSL_ERROR_WANT_WRITE ? (EPOLLIN | EPOLLOUT)
                                               : EPOLLIN;
        ev.data.ptr = c;
        epoll_ctl(w->epfd, EPOLL_CTL_MOD, c->fd, &ev);
        return;
    }
    close_conn(w, c);  // bad cert, protocol error, or peer gave up
}

void on_readable(Engine* E, Worker* w, Conn* c) {
    char buf[65536];
    for (;;) {
        int n = conn_read(c, buf, sizeof buf);
        if (n > 0) {
            c->in.append(buf, n);
            if (c->in.size() > (1ull << 31)) { close_conn(w, c); return; }
            continue;
        }
        if (n == -1) break;
        if (n == -3) {  // SSL_read blocked on WRITE: wake on writability
            struct epoll_event ev;
            ev.events = EPOLLIN | EPOLLOUT;
            ev.data.ptr = c;
            epoll_ctl(w->epfd, EPOLL_CTL_MOD, c->fd, &ev);
            break;
        }
        close_conn(w, c);  // EOF or error
        return;
    }
    c->last_active = time(nullptr);
    drain_buffered(E, w, c);
}

void* worker_main(void* arg) {
    auto* pair = (std::pair<Engine*, Worker*>*)arg;
    Engine* E = pair->first;
    Worker* w = pair->second;
    delete pair;
    struct epoll_event evs[256];
    time_t last_sweep = time(nullptr);
    while (E->running.load()) {
        int n = epoll_wait(w->epfd, evs, 256, 500);
        for (int i = 0; i < n; i++) {
            int kind = *(int*)evs[i].data.ptr;  // first field of both structs
            if (kind == 1) {
                BackendConn* b = (BackendConn*)evs[i].data.ptr;
                if (b->fd < 0) continue;
                on_backend_event(E, w, b, evs[i].events);
                continue;
            }
            Conn* c = (Conn*)evs[i].data.ptr;
            if (c->fd < 0) continue;  // closed earlier in this batch
            if (evs[i].events & (EPOLLHUP | EPOLLERR)) { close_conn(w, c); continue; }
            if (c->tls_hs == 1) {
                tls_handshake_step(E, w, c);
                if (c->fd < 0 || c->tls_hs != 2) continue;
                // fall through: the handshake's last flight may have
                // arrived together with the first request bytes
            }
            if (evs[i].events & EPOLLOUT) {
                flush_out(w, c);
                if (c->fd < 0) continue;
            }
            // EPOLLOUT (without EPOLLIN) also retries reads: a TLS read
            // that blocked on WRITE (conn_read -3) resumes on writability
            if (evs[i].events & (EPOLLIN | EPOLLOUT)) on_readable(E, w, c);
        }
        {
            std::lock_guard<std::mutex> l(w->conns_mu);
            for (auto* c : w->graveyard) delete c;
            w->graveyard.clear();
        }
        for (auto* b : w->back_graveyard) delete b;
        w->back_graveyard.clear();
        time_t now = time(nullptr);
        if (now - last_sweep > 30) {
            last_sweep = now;
            std::vector<Conn*> idle;
            {
                std::lock_guard<std::mutex> l(w->conns_mu);
                for (auto* c : w->conns)
                    if (now - c->last_active > 300 && c->upstream == nullptr)
                        idle.push_back(c);
            }
            for (auto* c : idle) close_conn(w, c);
            // Reclaim proxied requests: orphans (client gone) promptly,
            // client-attached ones only after an hour — admin operations
            // (vacuum, ec encode, tiering) legitimately run many minutes
            // and had no front-door timeout before this engine existed
            std::vector<BackendConn*> stuck;
            for (auto* b : w->pending) {
                long age = now - b->started;
                // the hour-long allowance is for proxied ADMIN operations
                // (vacuum, ec encode); filer chunk uploads/relays are
                // small-blob volume hops that answer in milliseconds —
                // a wedged one must fail the client fast
                long limit = b->mode != 0 ? 30 : 3600;
                if ((b->client == nullptr && age > 75) || age > limit)
                    stuck.push_back(b);
            }
            for (auto* b : stuck) backend_complete(E, w, b, false, false, false);
            // queued (capped) requests age out too: wedged in-flight
            // requests must not hang queued clients without a response
            std::vector<BackendConn*> stale_q;
            for (auto* b : w->waiting)
                if (b->client == nullptr || now - b->started > 600)
                    stale_q.push_back(b);
            for (auto* b : stale_q) {
                for (size_t i = 0; i < w->waiting.size(); i++)
                    if (w->waiting[i] == b) {
                        w->waiting.erase(w->waiting.begin() + i);
                        break;
                    }
                if (b->client) {
                    b->client->upstream = nullptr;
                    json_response(b->client, 504, "Gateway Timeout",
                                  "{\"error\": \"backend queue timeout\"}");
                    b->client->want_close = true;
                    flush_out(w, b->client);
                }
                w->back_graveyard.push_back(b);
            }
            for (auto* b : w->back_graveyard) delete b;
            w->back_graveyard.clear();
        }
    }
    {
        std::lock_guard<std::mutex> l(w->conns_mu);
        for (auto* c : w->conns) {
            if (c->ssl != nullptr) tls_api()->SSL_free(c->ssl);
            if (c->fd >= 0) close(c->fd);
            delete c;
        }
        w->conns.clear();
        for (auto* c : w->graveyard) delete c;
        w->graveyard.clear();
    }
    for (auto* b : w->pending) {
        back_free_ssl(b);
        if (b->fd >= 0) close(b->fd);
        delete b;
    }
    w->pending.clear();
    for (auto* b : w->waiting) delete b;
    w->waiting.clear();
    for (auto* b : w->back_graveyard) delete b;
    w->back_graveyard.clear();
    auto drain_pool = [](std::vector<std::pair<int, void*>>& pool) {
        for (auto& pooled : pool) {
            if (pooled.second != nullptr) tls_api()->SSL_free(pooled.second);
            close(pooled.first);
        }
        pool.clear();
    };
    drain_pool(w->idle_backends);
    for (auto& kv : w->idle_targets) drain_pool(kv.second);
    w->idle_targets.clear();
    return nullptr;
}

void* accept_main(void* arg) {
    Engine* E = (Engine*)arg;
    size_t next = 0;
    while (E->running.load()) {
        struct sockaddr_in sa;
        socklen_t sl = sizeof sa;
        int fd = accept(E->listen_fd, (struct sockaddr*)&sa, &sl);
        if (fd < 0) {
            if (errno == EINTR || errno == EAGAIN) continue;
            if (!E->running.load()) break;
            usleep(10000);
            continue;
        }
        set_nonblock(fd);
        int one = 1;
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
        Worker& w = E->workers[next % E->workers.size()];
        next++;
        Conn* c = new Conn();
        c->fd = fd;
        c->last_active = time(nullptr);
        if (E->tls_ctx != nullptr) {
            TlsApi* T = tls_api();
            c->ssl = T->SSL_new(E->tls_ctx);
            if (c->ssl == nullptr) { close(fd); delete c; continue; }
            T->SSL_set_fd(c->ssl, fd);
            T->SSL_set_accept_state(c->ssl);
            c->tls_hs = 1;  // handshake driven by epoll events
        }
        struct epoll_event ev;
        ev.events = EPOLLIN;
        ev.data.ptr = c;
        {
            std::lock_guard<std::mutex> l(w.conns_mu);
            w.conns.push_back(c);
        }
        epoll_ctl(w.epfd, EPOLL_CTL_ADD, fd, &ev);
    }
    return nullptr;
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

// returns an engine handle (>=0); the bound port comes from sw_fl_port().
// tls_cert non-empty turns on engine-terminated mTLS (client certs
// REQUIRED, CA = tls_ca, optional comma-separated '*'-glob CN allow-list);
// -4/-5 = TLS requested but unavailable/misconfigured, so the caller can
// fall back to serving TLS from Python.
int sw_fl_start(const char* host, int port, const char* backend_host,
                int backend_port, int workers, int secure_reads,
                int secure_writes, int max_backend,
                const char* jwt_write_key, const char* jwt_read_key,
                const char* tls_cert, const char* tls_key,
                const char* tls_ca, const char* tls_allowed_cns) {
    void* tls_ctx = nullptr;
    void* tls_client_ctx = nullptr;
    if (tls_cert && *tls_cert) {
        TlsApi* T = tls_api();
        if (T == nullptr) return -4;  // no OpenSSL runtime on this host
        tls_ctx = T->SSL_CTX_new(T->TLS_server_method());
        if (tls_ctx == nullptr) return -4;
        if (T->SSL_CTX_use_certificate_chain_file(tls_ctx, tls_cert) != 1 ||
            T->SSL_CTX_use_PrivateKey_file(tls_ctx, tls_key,
                                           kSSL_FILETYPE_PEM) != 1 ||
            (tls_ca && *tls_ca &&
             T->SSL_CTX_load_verify_locations(tls_ctx, tls_ca, nullptr) != 1)) {
            T->SSL_CTX_free(tls_ctx);
            return -5;
        }
        T->SSL_CTX_set_verify(
            tls_ctx, kSSL_VERIFY_PEER | kSSL_VERIFY_FAIL_IF_NO_PEER_CERT,
            nullptr);
        // partial writes: flush_out retries from a moving offset
        T->SSL_CTX_ctrl(tls_ctx, kSSL_CTRL_MODE,
                        kSSL_MODE_ENABLE_PARTIAL_WRITE |
                            kSSL_MODE_ACCEPT_MOVING_WRITE_BUFFER,
                        nullptr);
        // client context for upstream hops (filer engine -> volume engine
        // under mTLS): this node's cert doubles as the client cert, the
        // server's cert must chain to the CA (identity = CA + CN, no
        // hostname check — security/tls.py client semantics)
        tls_client_ctx = T->SSL_CTX_new(T->TLS_client_method());
        if (tls_client_ctx != nullptr) {
            if (T->SSL_CTX_use_certificate_chain_file(tls_client_ctx,
                                                      tls_cert) != 1 ||
                T->SSL_CTX_use_PrivateKey_file(tls_client_ctx, tls_key,
                                               kSSL_FILETYPE_PEM) != 1 ||
                (tls_ca && *tls_ca &&
                 T->SSL_CTX_load_verify_locations(tls_client_ctx, tls_ca,
                                                  nullptr) != 1)) {
                T->SSL_CTX_free(tls_client_ctx);
                tls_client_ctx = nullptr;  // upstream hops stay on Python
            } else {
                T->SSL_CTX_set_verify(tls_client_ctx, kSSL_VERIFY_PEER,
                                      nullptr);
                T->SSL_CTX_ctrl(tls_client_ctx, kSSL_CTRL_MODE,
                                kSSL_MODE_ENABLE_PARTIAL_WRITE |
                                    kSSL_MODE_ACCEPT_MOVING_WRITE_BUFFER,
                                nullptr);
            }
        }
    }
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) {
        if (tls_ctx) tls_api()->SSL_CTX_free(tls_ctx);
        if (tls_client_ctx) tls_api()->SSL_CTX_free(tls_client_ctx);
        return -2;
    }
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
    struct sockaddr_in sa;
    memset(&sa, 0, sizeof sa);
    sa.sin_family = AF_INET;
    sa.sin_port = htons(port);
    sa.sin_addr.s_addr = host && *host ? inet_addr(host) : htonl(INADDR_ANY);
    if (bind(fd, (struct sockaddr*)&sa, sizeof sa) != 0 ||
        listen(fd, 1024) != 0) {
        close(fd);
        if (tls_ctx) tls_api()->SSL_CTX_free(tls_ctx);
        if (tls_client_ctx) tls_api()->SSL_CTX_free(tls_client_ctx);
        return -3;
    }
    socklen_t sl = sizeof sa;
    getsockname(fd, (struct sockaddr*)&sa, &sl);
    Engine* E = new Engine();
    E->listen_fd = fd;
    E->port = ntohs(sa.sin_port);
    E->backend_port = backend_port;
    E->backend_ip = htonl(INADDR_LOOPBACK);
    if (backend_host && *backend_host &&
        strcmp(backend_host, "0.0.0.0") != 0) {
        uint32_t ip = inet_addr(backend_host);
        if (ip != INADDR_NONE) E->backend_ip = ip;
    }
    E->secure_reads = secure_reads != 0;
    E->secure_writes = secure_writes != 0;
    if (max_backend > 0) E->max_backend = (size_t)max_backend;
    // fixed before any worker/accept thread exists: workers read these
    // lock-free on the request path
    if (jwt_write_key && *jwt_write_key) E->jwt_write_key = jwt_write_key;
    if (jwt_read_key && *jwt_read_key) E->jwt_read_key = jwt_read_key;
    E->tls_ctx = tls_ctx;
    E->tls_client_ctx = tls_client_ctx;
    if (tls_allowed_cns && *tls_allowed_cns) {
        const char* p = tls_allowed_cns;
        while (*p) {
            const char* comma = strchr(p, ',');
            size_t n = comma ? (size_t)(comma - p) : strlen(p);
            while (n > 0 && (*p == ' ' || *p == '\t')) { p++; n--; }
            while (n > 0 && (p[n - 1] == ' ' || p[n - 1] == '\t')) n--;
            if (n > 0) E->allowed_cns.emplace_back(p, n);
            p = comma ? comma + 1 : p + n;
        }
    }
    if (workers < 1) workers = 2;
    if (workers > 32) workers = 32;
    E->workers.resize(workers);
    for (auto& w : E->workers) {
        w.epfd = epoll_create1(0);
        auto* pair = new std::pair<Engine*, Worker*>(E, &w);
        pthread_create(&w.thread, nullptr, worker_main, pair);
    }
    pthread_create(&E->accept_thread, nullptr, accept_main, E);
    std::lock_guard<std::mutex> gl(g_engine_mu);
    g_engines.push_back(E);
    return (int)g_engines.size() - 1;
}

int sw_fl_port(int h) {
    Engine* E = engine_at(h);
    return E ? E->port : -1;
}

void sw_fl_stop(int h) {
    Engine* E;
    {
        std::lock_guard<std::mutex> gl(g_engine_mu);
        if (h < 0 || (size_t)h >= g_engines.size()) return;
        E = g_engines[h];
        g_engines[h] = nullptr;
    }
    if (!E) return;
    E->running.store(false);
    shutdown(E->listen_fd, SHUT_RDWR);
    close(E->listen_fd);
    pthread_join(E->accept_thread, nullptr);
    for (auto& w : E->workers) {
        pthread_join(w.thread, nullptr);
        close(w.epfd);
    }
    if (E->tls_ctx != nullptr) tls_api()->SSL_CTX_free(E->tls_ctx);
    if (E->tls_client_ctx != nullptr)
        tls_api()->SSL_CTX_free(E->tls_client_ctx);
    if (E->filer_journal_fd >= 0) close(E->filer_journal_fd);
    delete E;
}

int sw_fl_register_volume(int h, uint32_t vid, int dat_fd, int idx_fd,
                          int version, unsigned long long tail,
                          unsigned long long last_append_ns, int readonly,
                          int forward_writes) {
    Engine* E = engine_at(h);
    if (!E) return -1;
    auto v = std::make_shared<Vol>();
    v->vid = vid;
    v->dat_fd = dat_fd;
    v->idx_fd = idx_fd;
    v->version = version;
    v->tail.store(tail);
    v->last_ns.store(last_append_ns);
    v->readonly.store(readonly != 0);
    v->forward_writes.store(forward_writes != 0);
    std::unique_lock<std::shared_mutex> l(E->reg_mu);
    E->vols[vid] = v;
    return 0;
}

// Tag a registered volume with its collection so sw_fl_get_usage can
// aggregate native-op counters per tenant (PR 16 ABI growth — the Python
// binding hasattr-gates this like every prior optional symbol).
int sw_fl_volume_collection_set(int h, uint32_t vid, const char* coll) {
    Engine* E = engine_at(h);
    if (!E) return -1;
    std::unique_lock<std::shared_mutex> l(E->reg_mu);
    auto it = E->vols.find(vid);
    if (it == E->vols.end()) return -2;
    const char* src = (coll != nullptr) ? coll : "";
    strncpy(it->second->collection, src, sizeof(it->second->collection) - 1);
    it->second->collection[sizeof(it->second->collection) - 1] = '\0';
    return 0;
}

// arms the data plane once the Python-side bulk map load has landed
int sw_fl_volume_serving(int h, uint32_t vid) {
    Engine* E = engine_at(h);
    if (!E) return -1;
    auto v = E->vol_raw(vid);
    if (!v) return -2;
    v->serving.store(true, std::memory_order_release);
    return 0;
}

int sw_fl_load_entries(int h, uint32_t vid, const uint64_t* keys,
                       const uint64_t* offsets, const int32_t* sizes,
                       size_t n) {
    Engine* E = engine_at(h);
    if (!E) return -1;
    auto v = E->vol_raw(vid);
    if (!v) return -2;
    std::unique_lock<std::shared_mutex> ml(v->map_mu);
    for (size_t i = 0; i < n; i++)
        if (sizes[i] > 0) v->nmap.put(keys[i], offsets[i], sizes[i]);
    return 0;
}

int sw_fl_unregister_volume(int h, uint32_t vid) {
    Engine* E = engine_at(h);
    if (!E) return -1;
    std::shared_ptr<Vol> v;
    {
        std::unique_lock<std::shared_mutex> l(E->reg_mu);
        auto it = E->vols.find(vid);
        if (it == E->vols.end()) return 0;
        v = it->second;
        E->vols.erase(it);
    }
    // wait out any in-flight append; readers hold the shared_ptr and the
    // fds stay open until the last reference drops
    v->append_mu.lock();
    v->append_mu.unlock();
    return 0;
}

int sw_fl_set_flags(int h, uint32_t vid, int readonly, int forward_writes) {
    Engine* E = engine_at(h);
    if (!E) return -1;
    auto v = E->vol_raw(vid);
    if (!v) return -2;
    v->readonly.store(readonly != 0);
    v->forward_writes.store(forward_writes != 0);
    return 0;
}

int sw_fl_volume_lock(int h, uint32_t vid) {
    Engine* E = engine_at(h);
    if (!E) return -1;
    auto v = E->vol_raw(vid);
    if (!v) return -2;
    v->append_mu.lock();
    return 0;
}

int sw_fl_volume_unlock(int h, uint32_t vid) {
    Engine* E = engine_at(h);
    if (!E) return -1;
    auto v = E->vol_raw(vid);
    if (!v) return -2;
    v->append_mu.unlock();
    return 0;
}

unsigned long long sw_fl_tail_get(int h, uint32_t vid) {
    Engine* E = engine_at(h);
    if (!E) return 0;
    auto v = E->vol_raw(vid);
    return v ? v->tail.load() : 0;
}

int sw_fl_tail_set(int h, uint32_t vid, unsigned long long tail,
                   unsigned long long last_ns) {
    Engine* E = engine_at(h);
    if (!E) return -1;
    auto v = E->vol_raw(vid);
    if (!v) return -2;
    v->tail.store(tail);
    if (last_ns) v->last_ns.store(last_ns);
    return 0;
}

// --- online-EC stripe accumulator ------------------------------------------
// Arms per-volume stripe tracking for the write-path erasure coder
// (storage/erasure_coding/online.py): stripe_bytes is one full row
// (DATA_SHARDS x block), watermark the .dat offset parity covers so far.
int sw_fl_ec_online_arm(int h, uint32_t vid, unsigned long long stripe_bytes,
                        unsigned long long watermark) {
    Engine* E = engine_at(h);
    if (!E) return -1;
    auto v = E->vol_raw(vid);
    if (!v) return -2;
    v->ec_stripe.store(stripe_bytes);
    v->ec_watermark.store(watermark);
    return 0;
}

// Complete stripes accumulated past the watermark (the drain hook's O(1)
// readiness check). out2 (optional, 2 slots) receives {watermark, tail}.
// -1 bad handle, -2 unknown volume, -3 not armed.
long long sw_fl_ec_online_pending(int h, uint32_t vid,
                                  unsigned long long* out2) {
    Engine* E = engine_at(h);
    if (!E) return -1;
    auto v = E->vol_raw(vid);
    if (!v) return -2;
    uint64_t stripe = v->ec_stripe.load(std::memory_order_relaxed);
    uint64_t wm = v->ec_watermark.load(std::memory_order_relaxed);
    uint64_t tail = v->tail.load(std::memory_order_relaxed);
    if (out2 != nullptr) {
        out2[0] = wm;
        out2[1] = tail;
    }
    if (stripe == 0) return -3;
    if (tail <= wm) return 0;
    return (long long)((tail - wm) / stripe);
}

int sw_fl_ec_online_advance(int h, uint32_t vid,
                            unsigned long long watermark) {
    Engine* E = engine_at(h);
    if (!E) return -1;
    auto v = E->vol_raw(vid);
    if (!v) return -2;
    v->ec_watermark.store(watermark);
    return 0;
}

int sw_fl_map_put(int h, uint32_t vid, uint64_t key, unsigned long long offset,
                  int32_t size) {
    Engine* E = engine_at(h);
    if (!E) return -1;
    auto v = E->vol_raw(vid);
    if (!v) return -2;
    std::unique_lock<std::shared_mutex> ml(v->map_mu);
    if (size > 0) v->nmap.put(key, offset, size);
    else v->nmap.del(key);
    return 0;
}

// install/replace the assign responder for one exact query string.
// tails: n zero-terminated JSON fragments (everything after the fid field).
int sw_fl_assign_set(int h, const char* query, const uint32_t* vids,
                     const char* tails, size_t n,
                     unsigned long long key_start,
                     unsigned long long key_end) {
    Engine* E = engine_at(h);
    if (!E || n == 0) return -1;
    auto ap = std::make_shared<AssignProfile>();
    ap->vids.assign(vids, vids + n);
    const char* p = tails;
    for (size_t i = 0; i < n; i++) {
        ap->tails.emplace_back(p);
        p += strlen(p) + 1;
    }
    ap->next_key.store(key_start);
    ap->end_key = key_end;
    std::unique_lock<std::shared_mutex> l(E->assign_mu);
    E->assigns[query] = ap;
    return 0;
}

int sw_fl_assign_clear(int h) {
    Engine* E = engine_at(h);
    if (!E) return -1;
    std::unique_lock<std::shared_mutex> l(E->assign_mu);
    E->assigns.clear();
    return 0;
}

// --- filer mode --------------------------------------------------------------

// turn on the native filer paths. journal_path: entry WAL appended before
// every native-write ack (crash replay); "" disables journaling (memory
// stores). compress: the Python pipeline would compress compressible
// mimes, so chunk-backed native writes restrict to incompressible ones.
int sw_fl_filer_enable(int h, const char* journal_path,
                       unsigned long long chunk_limit, int compress) {
    Engine* E = engine_at(h);
    if (!E) return -1;
    if (journal_path && *journal_path) {
        int fd = open(journal_path, O_WRONLY | O_APPEND | O_CREAT, 0644);
        if (fd < 0) return -2;
        E->filer_journal_fd = fd;
    }
    if (chunk_limit > 0) E->filer_chunk_limit = (size_t)chunk_limit;
    E->filer_compress = compress != 0;
    E->filer_mode.store(true, std::memory_order_release);
    return 0;
}

// can this engine reach (possibly TLS) upstream targets natively? Under
// mTLS that needs the client context; plaintext clusters always can.
int sw_fl_tls_client_ok(int h) {
    Engine* E = engine_at(h);
    if (!E) return 0;
    return (E->tls_ctx == nullptr || E->tls_client_ctx != nullptr) ? 1 : 0;
}

// typed error strings for the negative rcs this ABI returns — the Python
// side logs these instead of a bare rc so a fallback regime names itself
const char* sw_fl_error_str(int rc) {
    switch (rc) {
        case 0: return "ok";
        case -1: return "engine handle invalid or already stopped";
        case -2: return "host is not an IPv4 address (hostname targets"
                        " stay on the Python path)";
        case -3: return "mTLS configured but no native TLS client context"
                        " (OpenSSL runtime missing)";
        case -4: return "TLS requested but OpenSSL runtime unavailable";
        case -5: return "TLS certificate/key/CA failed to load";
        default: return "unknown error";
    }
}

// upsert one volume's lease into the POOL (keyed by vid): chunk writes
// round-robin across unspent leases, and a failed volume drops only its
// own entry. Python tops the pool up via sw_fl_filer_lease_count.
int sw_fl_filer_lease_set(int h, const char* vol_host, int vol_port,
                          uint32_t vid, uint32_t cookie,
                          unsigned long long key_start,
                          unsigned long long key_end, const char* upload_auth,
                          const char* read_auth) {
    Engine* E = engine_at(h);
    if (!E) return -1;
    if (E->tls_ctx != nullptr && E->tls_client_ctx == nullptr)
        return -3;  // mTLS without a client ctx: uploads would hit a TLS
                    // listener in plaintext and 500 — stay on Python
    auto L = std::make_shared<FilerLease>();
    L->vol_ip = htonl(INADDR_LOOPBACK);
    if (vol_host && *vol_host && strcmp(vol_host, "0.0.0.0") != 0) {
        uint32_t ip = inet_addr(vol_host);
        if (ip == INADDR_NONE) return -2;  // hostname: Python path only
        L->vol_ip = ip;
    }
    L->vol_port = vol_port;
    L->vid = vid;
    L->cookie = cookie;
    L->next_key.store(key_start);
    L->end_key = key_end;
    if (upload_auth && *upload_auth) L->auth = upload_auth;
    std::unique_lock<std::shared_mutex> l(E->flease_mu);
    bool replaced = false;
    for (auto& ex : E->fleases)
        if (ex->vid == vid) {
            uint64_t next = ex->next_key.load(std::memory_order_relaxed);
            if (next < ex->end_key && ex->end_key - next >= 5000) {
                // the held range is still healthy: inherit it instead of
                // replacing (a replace abandons the unspent keys — on a
                // cluster with fewer writable volumes than the pool
                // target every top-up probe lands on an already-held
                // vid, and the discard would waste ~count fids per probe
                // forever) while refreshing endpoint + auth so a
                // slow-draining range never outlives its JWT. The swap
                // is safe under the unique lock: take_filer_lease mints
                // under the shared lock, so no key can be drawn between
                // the next_key load and the pointer swap, and in-flight
                // writers hold their own shared_ptr to the immutable old
                // object. rc=1 tells the filer the master granted a
                // duplicate vid — the pool is as wide as the cluster
                // allows, stop topping up.
                L->cookie = ex->cookie;
                L->next_key.store(next);
                L->end_key = ex->end_key;
                ex = std::move(L);
                E->filer_read_auth =
                    read_auth && *read_auth ? read_auth : "";
                return 1;
            }
            ex = std::move(L);
            replaced = true;
            break;
        }
    if (!replaced) E->fleases.push_back(std::move(L));
    E->filer_read_auth = read_auth && *read_auth ? read_auth : "";
    return 0;
}

unsigned long long sw_fl_filer_lease_remaining(int h) {
    Engine* E = engine_at(h);
    if (!E) return 0;
    std::shared_lock<std::shared_mutex> l(E->flease_mu);
    uint64_t total = 0;
    for (const auto& L : E->fleases) {
        uint64_t next = L->next_key.load(std::memory_order_relaxed);
        if (next < L->end_key) total += L->end_key - next;
    }
    return total;
}

// live (unspent) leases in the pool; -1 = bad handle so the Python side
// can tell "engine stopped" from "pool empty" (the r05 shutdown race
// logged a bare rc=-1 exactly because lease_remaining conflated the two)
long sw_fl_filer_lease_count(int h) {
    Engine* E = engine_at(h);
    if (!E) return -1;
    std::shared_lock<std::shared_mutex> l(E->flease_mu);
    long n = 0;
    for (const auto& L : E->fleases)
        if (L->next_key.load(std::memory_order_relaxed) < L->end_key) n++;
    return n;
}

int sw_fl_filer_cache_put(int h, const char* path, const char* host,
                          int port, const char* fid, const char* mime,
                          const char* md5_hex, unsigned long long size,
                          unsigned long long mtime, const void* inline_data,
                          size_t inline_len) {
    Engine* E = engine_at(h);
    if (!E) return -1;
    auto ent = std::make_shared<FilerCacheEnt>();
    if (inline_len > 0) {
        ent->inline_data.assign((const char*)inline_data, inline_len);
    } else {
        ent->ip = htonl(INADDR_LOOPBACK);
        if (host && *host && strcmp(host, "0.0.0.0") != 0) {
            uint32_t ip = inet_addr(host);
            if (ip == INADDR_NONE) return -2;
            ent->ip = ip;
        }
        ent->port = port;
        ent->fid = fid ? fid : "";
        if (ent->fid.empty()) return -3;
    }
    ent->mime = mime ? mime : "";
    ent->md5_hex = md5_hex ? md5_hex : "";
    ent->size = size;
    ent->mtime = mtime;
    fcache_put(E, path, std::move(ent));
    return 0;
}

// install the fs.configure rule prefixes (NUL-joined, n entries):
// native writes under them defer to Python
int sw_fl_filer_rules_set(int h, const char* prefixes, size_t n) {
    Engine* E = engine_at(h);
    if (!E) return -1;
    std::vector<std::string> out;
    const char* p = prefixes;
    for (size_t i = 0; i < n; i++) {
        out.emplace_back(p);
        p += out.back().size() + 1;
    }
    std::unique_lock<std::shared_mutex> l(E->frules_mu);
    E->frule_prefixes = std::move(out);
    return 0;
}

int sw_fl_filer_cache_del(int h, const char* path) {
    Engine* E = engine_at(h);
    if (!E) return -1;
    fcache_del(E, path ? path : "");
    return 0;
}

// pop queued entry frames into `out` (whole frames only); returns bytes
long sw_fl_filer_drain(int h, uint8_t* out, size_t cap) {
    Engine* E = engine_at(h);
    if (!E) return -1;
    std::lock_guard<std::mutex> l(E->filer_mu);
    size_t off = 0;
    while (!E->filer_events.empty()) {
        const std::string& f = E->filer_events.front();
        if (off + f.size() > cap) break;
        memcpy(out + off, f.data(), f.size());
        off += f.size();
        E->filer_events_bytes -= f.size();
        E->filer_events.pop_front();
    }
    return (long)off;
}

// truncate the journal once Python has applied everything it drained.
// Refuses (returns pending count) while frames are still queued — those
// would be lost to a crash between truncate and their drain.
long sw_fl_filer_journal_reset(int h) {
    Engine* E = engine_at(h);
    if (!E) return -1;
    std::lock_guard<std::mutex> l(E->filer_mu);
    if (!E->filer_events.empty()) return (long)E->filer_events.size();
    if (E->filer_journal_fd >= 0) {
        if (ftruncate(E->filer_journal_fd, 0) != 0) return -2;
        lseek(E->filer_journal_fd, 0, SEEK_SET);
    }
    return 0;
}

long sw_fl_drain_events(int h, uint8_t* out, size_t max_events) {
    Engine* E = engine_at(h);
    if (!E) return -1;
    std::lock_guard<std::mutex> l(E->ev_mu);
    size_t n = E->events.size() < max_events ? E->events.size() : max_events;
    for (size_t i = 0; i < n; i++) {
        memcpy(out + i * sizeof(Event), &E->events.front(), sizeof(Event));
        E->events.pop_front();
    }
    return (long)n;
}

void sw_fl_get_stats(int h, unsigned long long* out6) {
    Engine* E = engine_at(h);
    if (!E) { memset(out6, 0, 6 * sizeof(unsigned long long)); return; }
    out6[0] = E->stats.requests.load();
    out6[1] = E->stats.native_reads.load();
    out6[2] = E->stats.native_writes.load();
    out6[3] = E->stats.native_deletes.load();
    out6[4] = E->stats.proxied.load();
    out6[5] = E->stats.native_assigns.load();
}

// Self-describing per-op metrics snapshot (PR 2 observability ABI —
// storage/fastlane.py binds it OPTIONALLY, so a prebuilt .so without this
// symbol keeps working with plain sw_fl_get_stats). Layout:
//   out[0] = n_ops   (read, write, delete, assign, proxied — in order)
//   out[1] = n_buckets (finite bucket bounds; each op then carries
//            n_buckets+1 counters, the last being the +Inf overflow)
//   out[2 .. 2+n_buckets)  bucket upper bounds in NANOSECONDS
//   then per op: count, bytes, ns_sum, bucket[n_buckets+1]
// Returns u64 values written; -1 bad handle, -2 cap too small.
long sw_fl_get_metrics(int h, unsigned long long* out, size_t cap) {
    Engine* E = engine_at(h);
    if (!E) return -1;
    size_t need = 2 + kLatBuckets + (size_t)kNumOps * (3 + kLatBuckets + 1);
    if (cap < need) return -2;
    size_t o = 0;
    out[o++] = (unsigned long long)kNumOps;
    out[o++] = (unsigned long long)kLatBuckets;
    for (int i = 0; i < kLatBuckets; i++) out[o++] = kLatBoundsNs[i];
    for (int op = 0; op < kNumOps; op++) {
        OpStat& s = E->op_stats[op];
        out[o++] = s.count.load(std::memory_order_relaxed);
        out[o++] = s.bytes.load(std::memory_order_relaxed);
        out[o++] = s.ns_sum.load(std::memory_order_relaxed);
        for (int i = 0; i <= kLatBuckets; i++)
            out[o++] = s.buckets[i].load(std::memory_order_relaxed);
    }
    return (long)o;
}

// Front-door accounting snapshot. Layout:
//   out[0] = n_ops (read, write, delete — kNumFrontOps)
//   out[1] = n_reasons (kNumFbReasons, in the kFb* order)
//   out[2 .. 2+n_ops)                     native counts per op
//   then n_ops rows of n_reasons fallback counts
// Returns u64s written; -1 bad handle, -2 cap too small.
long sw_fl_front_metrics(int h, unsigned long long* out, size_t cap) {
    Engine* E = engine_at(h);
    if (!E) return -1;
    size_t need = 2 + kNumFrontOps + (size_t)kNumFrontOps * kNumFbReasons;
    if (cap < need) return -2;
    size_t o = 0;
    out[o++] = (unsigned long long)kNumFrontOps;
    out[o++] = (unsigned long long)kNumFbReasons;
    for (int op = 0; op < kNumFrontOps; op++)
        out[o++] = E->fr_native[op].load(std::memory_order_relaxed);
    for (int op = 0; op < kNumFrontOps; op++)
        for (int r = 0; r < kNumFbReasons; r++)
            out[o++] = E->fr_fallback[op][r].load(std::memory_order_relaxed);
    return (long)o;
}

// --- s3 front mode -----------------------------------------------------------

// point the gateway's engine at the FILER's front door; object GET/PUT/
// DELETE on natively-flagged buckets then relay without touching Python
int sw_fl_s3_enable(int h, const char* filer_host, int filer_port) {
    Engine* E = engine_at(h);
    if (!E) return -1;
    if (E->tls_ctx != nullptr && E->tls_client_ctx == nullptr) return -3;
    uint32_t ip = htonl(INADDR_LOOPBACK);
    if (filer_host && *filer_host && strcmp(filer_host, "0.0.0.0") != 0) {
        ip = inet_addr(filer_host);
        if (ip == INADDR_NONE) return -2;  // hostname: Python path only
    }
    E->s3_filer_ip = ip;
    E->s3_filer_port = filer_port;
    E->s3_mode.store(true, std::memory_order_release);
    return 0;
}

int sw_fl_s3_disable(int h) {
    Engine* E = engine_at(h);
    if (!E) return -1;
    E->s3_mode.store(false, std::memory_order_release);
    std::unique_lock<std::shared_mutex> l(E->s3_mu);
    E->s3_buckets.clear();
    E->s3_uploads.clear();
    return 0;
}

// flags: kS3Read|kS3Write|kS3Delete bits; negative = forget the bucket
int sw_fl_s3_bucket_set(int h, const char* bucket, int flags) {
    Engine* E = engine_at(h);
    if (!E || !bucket || !*bucket) return -1;
    std::unique_lock<std::shared_mutex> l(E->s3_mu);
    if (flags < 0) E->s3_buckets.erase(bucket);
    else E->s3_buckets[bucket] = flags;
    return 0;
}

// multipart upload registry: parts for unknown uploadIds proxy to Python
// (which answers NoSuchUpload); create/complete/abort maintain it
int sw_fl_s3_upload_set(int h, const char* bucket, const char* upload_id,
                        int on) {
    Engine* E = engine_at(h);
    if (!E || !bucket || !upload_id) return -1;
    std::string key = std::string(bucket) + "/" + upload_id;
    std::unique_lock<std::shared_mutex> l(E->s3_mu);
    if (on) E->s3_uploads.insert(std::move(key));
    else E->s3_uploads.erase(key);
    return 0;
}

// Per-volume native-op counters: out6 = reads, writes, deletes,
// read_bytes, write_bytes, tail. Returns 0; -1 bad handle, -2 no volume.
int sw_fl_get_volume_metrics(int h, uint32_t vid, unsigned long long* out6) {
    Engine* E = engine_at(h);
    if (!E) return -1;
    auto v = E->vol_raw(vid);
    if (!v) return -2;
    out6[0] = v->m_reads.load(std::memory_order_relaxed);
    out6[1] = v->m_writes.load(std::memory_order_relaxed);
    out6[2] = v->m_deletes.load(std::memory_order_relaxed);
    out6[3] = v->m_read_bytes.load(std::memory_order_relaxed);
    out6[4] = v->m_write_bytes.load(std::memory_order_relaxed);
    out6[5] = v->tail.load(std::memory_order_relaxed);
    return 0;
}

// Per-collection usage rollup over every registered volume's native-op
// counters. Text exposition (one line per collection, tab-separated):
//   <collection>\t<reads>\t<writes>\t<deletes>\t<read_bytes>\t<write_bytes>\n
// Untagged volumes aggregate under the empty collection name (the Python
// side maps it to its configured default). Returns bytes written;
// -1 bad handle, -2 cap too small for the full snapshot.
long sw_fl_get_usage(int h, char* out, size_t cap) {
    Engine* E = engine_at(h);
    if (!E) return -1;
    std::map<std::string, std::array<unsigned long long, 5>> agg;
    {
        std::shared_lock<std::shared_mutex> l(E->reg_mu);
        for (auto& kv : E->vols) {
            Vol* v = kv.second.get();
            auto& row = agg[std::string(v->collection)];
            row[0] += v->m_reads.load(std::memory_order_relaxed);
            row[1] += v->m_writes.load(std::memory_order_relaxed);
            row[2] += v->m_deletes.load(std::memory_order_relaxed);
            row[3] += v->m_read_bytes.load(std::memory_order_relaxed);
            row[4] += v->m_write_bytes.load(std::memory_order_relaxed);
        }
    }
    size_t o = 0;
    for (auto& kv : agg) {
        char line[256];
        int n = snprintf(line, sizeof(line),
                         "%s\t%llu\t%llu\t%llu\t%llu\t%llu\n",
                         kv.first.c_str(), kv.second[0], kv.second[1],
                         kv.second[2], kv.second[3], kv.second[4]);
        if (n < 0) continue;
        if (o + (size_t)n > cap) return -2;
        memcpy(out + o, line, (size_t)n);
        o += (size_t)n;
    }
    return (long)o;
}

}  // extern "C"
