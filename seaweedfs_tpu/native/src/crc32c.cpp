// CRC32-Castagnoli, slice-by-8, matching Go hash/crc32 Update semantics.
// CPU stand-in for the stdlib SSE4.2 asm the reference relies on
// (weed/storage/needle/crc.go:12). Uses the SSE4.2 instruction when the
// compiler makes it available via -march=native.
#include <cstdint>
#include <cstddef>
#include <cstring>
#include <algorithm>
#include <vector>

#if defined(__SSE4_2__)
#include <nmmintrin.h>
#endif

namespace {

uint32_t tables[8][256];

// C++11 magic static: thread-safe one-time build (the old plain-bool
// guard was a data race when several engine workers hashed concurrently
// on the table fallback path)
void init_tables() {
    static const bool built = [] {
        const uint32_t poly = 0x82F63B78u;
        for (uint32_t i = 0; i < 256; i++) {
            uint32_t c = i;
            for (int k = 0; k < 8; k++) c = (c & 1) ? (c >> 1) ^ poly : c >> 1;
            tables[0][i] = c;
        }
        for (int t = 1; t < 8; t++)
            for (uint32_t i = 0; i < 256; i++)
                tables[t][i] =
                    tables[t - 1][i] >> 8 ^ tables[0][tables[t - 1][i] & 0xFF];
        return true;
    }();
    (void)built;
}

} // namespace

#if defined(__SSE4_2__)
// continue a raw (pre-inversion) crc state over a tail; returns raw state
static uint32_t sw_crc32c_tail(uint32_t c, const unsigned char* data, size_t n) {
    while (n >= 8) {
        uint64_t v;
        std::memcpy(&v, data, 8);
        c = (uint32_t)_mm_crc32_u64(c, v);
        data += 8;
        n -= 8;
    }
    while (n--) c = _mm_crc32_u8(c, *data++);
    return c;
}
#endif

extern "C" uint32_t sw_crc32c_update(uint32_t crc, const unsigned char* data, size_t n) {
    uint32_t c = ~crc;
#if defined(__SSE4_2__)
    return ~sw_crc32c_tail(c, data, n);
#else
    init_tables();
    while (n >= 8) {
        uint64_t v;
        std::memcpy(&v, data, 8);
        v ^= c;
        c = tables[7][v & 0xFF] ^ tables[6][(v >> 8) & 0xFF] ^
            tables[5][(v >> 16) & 0xFF] ^ tables[4][(v >> 24) & 0xFF] ^
            tables[3][(v >> 32) & 0xFF] ^ tables[2][(v >> 40) & 0xFF] ^
            tables[1][(v >> 48) & 0xFF] ^ tables[0][(v >> 56) & 0xFF];
        data += 8;
        n -= 8;
    }
    while (n--) c = tables[0][(c ^ *data++) & 0xFF] ^ (c >> 8);
    return ~c;
#endif
}

// Batch variant for the upload-path hash service: n equal-length blobs,
// contiguous, one GIL-released call (mirrors sw_md5_batch's shape).
// Three independent blobs advance per loop: the crc32 instruction's
// 3-cycle latency serializes a single chain at ~5.5 GB/s, but three
// interleaved chains fill the pipeline (~3x) with no combine step needed.
extern "C" void sw_crc32c_batch(const unsigned char* blobs, size_t n,
                                size_t blob_len, uint32_t* out) {
#if defined(__SSE4_2__)
    size_t i = 0;
    for (; i + 3 <= n; i += 3) {
        const unsigned char* p0 = blobs + i * blob_len;
        const unsigned char* p1 = p0 + blob_len;
        const unsigned char* p2 = p1 + blob_len;
        uint32_t c0 = ~0u, c1 = ~0u, c2 = ~0u;
        size_t k = 0;
        for (; k + 8 <= blob_len; k += 8) {
            uint64_t v0, v1, v2;
            std::memcpy(&v0, p0 + k, 8);
            std::memcpy(&v1, p1 + k, 8);
            std::memcpy(&v2, p2 + k, 8);
            c0 = (uint32_t)_mm_crc32_u64(c0, v0);
            c1 = (uint32_t)_mm_crc32_u64(c1, v1);
            c2 = (uint32_t)_mm_crc32_u64(c2, v2);
        }
        for (; k < blob_len; k++) {
            c0 = _mm_crc32_u8(c0, p0[k]);
            c1 = _mm_crc32_u8(c1, p1[k]);
            c2 = _mm_crc32_u8(c2, p2[k]);
        }
        out[i] = ~c0;
        out[i + 1] = ~c1;
        out[i + 2] = ~c2;
    }
    for (; i < n; i++)
        out[i] = sw_crc32c_update(0, blobs + i * blob_len, blob_len);
#else
    for (size_t i = 0; i < n; i++)
        out[i] = sw_crc32c_update(0, blobs + i * blob_len, blob_len);
#endif
}

// Variable-length batch (CDC dedup chunks have content-defined lengths).
// Triplet-interleaved like sw_crc32c_batch; callers length-sort, so the
// three chains stay balanced and the shared prefix runs pipelined.
extern "C" void sw_crc32c_batch_var(const unsigned char* const* ptrs,
                                    const size_t* lens, size_t n,
                                    uint32_t* out) {
#if defined(__SSE4_2__)
    size_t i = 0;
    for (; i + 3 <= n; i += 3) {
        size_t common = lens[i];
        if (lens[i + 1] < common) common = lens[i + 1];
        if (lens[i + 2] < common) common = lens[i + 2];
        uint32_t c0 = ~0u, c1 = ~0u, c2 = ~0u;
        size_t k = 0;
        for (; k + 8 <= common; k += 8) {
            uint64_t v0, v1, v2;
            std::memcpy(&v0, ptrs[i] + k, 8);
            std::memcpy(&v1, ptrs[i + 1] + k, 8);
            std::memcpy(&v2, ptrs[i + 2] + k, 8);
            c0 = (uint32_t)_mm_crc32_u64(c0, v0);
            c1 = (uint32_t)_mm_crc32_u64(c1, v1);
            c2 = (uint32_t)_mm_crc32_u64(c2, v2);
        }
        out[i] = ~sw_crc32c_tail(c0, ptrs[i] + k, lens[i] - k);
        out[i + 1] = ~sw_crc32c_tail(c1, ptrs[i + 1] + k, lens[i + 1] - k);
        out[i + 2] = ~sw_crc32c_tail(c2, ptrs[i + 2] + k, lens[i + 2] - k);
    }
    for (; i < n; i++)
        out[i] = sw_crc32c_update(0, ptrs[i], lens[i]);
#else
    for (size_t i = 0; i < n; i++)
        out[i] = sw_crc32c_update(0, ptrs[i], lens[i]);
#endif
}

// Span batch over one contiguous buffer: length-sort and delegate to the
// interleaved var kernel (mirrors sw_md5_batch_spans) — CDC span lengths
// vary, and balanced triplets are what make the 3-chain pipeline engage.
extern "C" void sw_crc32c_batch_spans(const unsigned char* base,
                                      const size_t* offs, const size_t* lens,
                                      size_t n, uint32_t* out) {
    if (n == 0) return;
    std::vector<size_t> order(n);
    for (size_t i = 0; i < n; i++) order[i] = i;
    std::sort(order.begin(), order.end(),
              [&](size_t a, size_t b) { return lens[a] > lens[b]; });
    std::vector<const unsigned char*> ptrs(n);
    std::vector<size_t> slens(n);
    for (size_t i = 0; i < n; i++) {
        ptrs[i] = base + offs[order[i]];
        slens[i] = lens[order[i]];
    }
    std::vector<uint32_t> tmp(n);
    sw_crc32c_batch_var(ptrs.data(), slens.data(), n, tmp.data());
    for (size_t i = 0; i < n; i++) out[order[i]] = tmp[i];
}
