// CRC32-Castagnoli, slice-by-8, matching Go hash/crc32 Update semantics.
// CPU stand-in for the stdlib SSE4.2 asm the reference relies on
// (weed/storage/needle/crc.go:12). Uses the SSE4.2 instruction when the
// compiler makes it available via -march=native.
#include <cstdint>
#include <cstddef>
#include <cstring>

#if defined(__SSE4_2__)
#include <nmmintrin.h>
#endif

namespace {

uint32_t tables[8][256];
bool tables_ready = false;

void init_tables() {
    if (tables_ready) return;
    const uint32_t poly = 0x82F63B78u;
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++) c = (c & 1) ? (c >> 1) ^ poly : c >> 1;
        tables[0][i] = c;
    }
    for (int t = 1; t < 8; t++)
        for (uint32_t i = 0; i < 256; i++)
            tables[t][i] = tables[t - 1][i] >> 8 ^ tables[0][tables[t - 1][i] & 0xFF];
    tables_ready = true;
}

} // namespace

extern "C" uint32_t sw_crc32c_update(uint32_t crc, const unsigned char* data, size_t n) {
    uint32_t c = ~crc;
#if defined(__SSE4_2__)
    while (n >= 8) {
        uint64_t v;
        std::memcpy(&v, data, 8);
        c = (uint32_t)_mm_crc32_u64(c, v);
        data += 8;
        n -= 8;
    }
    while (n--) c = _mm_crc32_u8(c, *data++);
    return ~c;
#else
    init_tables();
    while (n >= 8) {
        uint64_t v;
        std::memcpy(&v, data, 8);
        v ^= c;
        c = tables[7][v & 0xFF] ^ tables[6][(v >> 8) & 0xFF] ^
            tables[5][(v >> 16) & 0xFF] ^ tables[4][(v >> 24) & 0xFF] ^
            tables[3][(v >> 32) & 0xFF] ^ tables[2][(v >> 40) & 0xFF] ^
            tables[1][(v >> 48) & 0xFF] ^ tables[0][(v >> 56) & 0xFF];
        data += 8;
        n -= 8;
    }
    while (n--) c = tables[0][(c ^ *data++) & 0xFF] ^ (c >> 8);
    return ~c;
#endif
}

// Batch variant for the upload-path hash service: n equal-length blobs,
// contiguous, one GIL-released call (mirrors sw_md5_batch's shape).
extern "C" void sw_crc32c_batch(const unsigned char* blobs, size_t n,
                                size_t blob_len, uint32_t* out) {
    for (size_t i = 0; i < n; i++)
        out[i] = sw_crc32c_update(0, blobs + i * blob_len, blob_len);
}

// Variable-length batch (CDC dedup chunks have content-defined lengths).
extern "C" void sw_crc32c_batch_var(const unsigned char* const* ptrs,
                                    const size_t* lens, size_t n,
                                    uint32_t* out) {
    for (size_t i = 0; i < n; i++)
        out[i] = sw_crc32c_update(0, ptrs[i], lens[i]);
}

// Span batch over one contiguous buffer (see sw_md5_batch_spans).
extern "C" void sw_crc32c_batch_spans(const unsigned char* base,
                                      const size_t* offs, const size_t* lens,
                                      size_t n, uint32_t* out) {
    for (size_t i = 0; i < n; i++)
        out[i] = sw_crc32c_update(0, base + offs[i], lens[i]);
}
