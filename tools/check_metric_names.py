#!/usr/bin/env python3
"""Prometheus metric-namespace lint: SeaweedFS_<subsystem>_<name>[_unit][_total].

Walks every family the process registry can expose — the counters and
histograms registered at import/enable time, the lazily-created kernel
families (stats/trace.py), and the collector-declared names the master and
volume servers export (topology gauges, fastlane engine series) — and
fails on any name violating the convention, so the metric namespace cannot
drift PR over PR. Conventions enforced:

  * name matches  SeaweedFS_<subsystem>_<snake_case>  with a known
    subsystem (master, volume, filer, s3, http, stats, mount, mq, iam,
    alerts, process, maintenance)
  * counters end in _total
  * histograms end in a base unit (_seconds or _bytes)
  * gauges do not end in _total (that suffix promises counter semantics)
  * alert-rule names (they ride into SeaweedFS_alerts_firing{alert=...})
    are unique snake_case with a known severity
  * maintenance task-type names (they ride into the `task` label of every
    SeaweedFS_maintenance_* family) are unique snake_case

`SeaweedFS_build_info` is the one subsystem-less exception — the
Prometheus build-info convention (`<binary>_build_info`).

Invoked from the tier-1 suite (tests/test_formats.py) and standalone:

    python tools/check_metric_names.py
"""

from __future__ import annotations

import os
import re
import sys

NAME_RE = re.compile(
    r"^SeaweedFS_"
    r"(master|volume|filer|s3|http|stats|mount|mq|iam|alerts|process"
    r"|maintenance|faults|events|slo|usage|heat|node|cluster|telemetry"
    r"|qos)_"
    r"[a-z][a-z0-9]*(_[a-z0-9]+)*$"
)

# fault-point names: dotted lowercase, at least two segments
FAULT_POINT_RE = re.compile(
    r"^[a-z][a-z0-9_]*(\.[a-z][a-z0-9_]*)+$"
)

# Prometheus build-info convention: no subsystem segment
SPECIAL_NAMES = {"SeaweedFS_build_info"}

ALERT_RULE_RE = re.compile(r"^[a-z][a-z0-9]*(_[a-z0-9]+)*$")
ALERT_SEVERITIES = {"critical", "warning"}

HISTOGRAM_UNITS = ("_seconds", "_bytes")


def collect() -> tuple[dict[str, str], list[str]]:
    """-> ({family: kind} for registry-backed metrics, [collector names])."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from seaweedfs_tpu import maintenance
    from seaweedfs_tpu.server.httpd import HTTPService
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume import VolumeServer
    from seaweedfs_tpu.stats import alerts, default_registry, history, \
        profiler, trace
    from seaweedfs_tpu.storage import crc
    from seaweedfs_tpu.storage.erasure_coding import encoder as ec_encoder

    # force the lazily-registered families into the registry
    for fam in (trace.EC_ENCODE_SECONDS, trace.EC_DECODE_SECONDS,
                trace.FILER_HASH_SECONDS, crc.VOLUME_CRC32C_SECONDS):
        trace._kernel_metrics(fam)
    ec_encoder._pipeline_hist()  # SeaweedFS_volume_ec_pipeline_seconds
    from seaweedfs_tpu.storage.erasure_coding import online as ec_online

    ec_online.ensure_metrics()  # SeaweedFS_volume_ec_online_* families
    from seaweedfs_tpu.storage.erasure_coding import decoder as ec_decoder

    ec_decoder.repair_metrics()  # SeaweedFS_volume_ec_repair_* families
    ec_decoder.stream_metrics()  # streaming-session chunk/resume families
    maintenance.ensure_metrics()  # SeaweedFS_maintenance_* families
    from seaweedfs_tpu.maintenance import scheduler as sched_mod

    sched_mod.lazy_batch_counter()  # SeaweedFS_maintenance_lazy_batch_total
    from seaweedfs_tpu.maintenance import scrub as scrub_mod

    scrub_mod.ensure_metrics()  # SeaweedFS_volume_scrub_* families
    from seaweedfs_tpu.stats import store as store_mod

    store_mod.ensure_metrics()  # SeaweedFS_telemetry_* spool families
    from seaweedfs_tpu.storage.volume import degraded_reads_counter
    from seaweedfs_tpu.util import faults as faults_mod

    faults_mod._injected_counter()  # SeaweedFS_faults_injected_total
    degraded_reads_counter()  # SeaweedFS_volume_degraded_reads_total
    svc = HTTPService(port=0)  # never started: registration side effect only
    svc.enable_metrics("lint", serve_route=False)
    reg = default_registry()
    reg.counter("SeaweedFS_stats_push_errors_total",
                "failed pushes to the metrics gateway", ("role",))
    with reg._lock:
        kinds = {name: m.kind for name, m in reg._metrics.items()}
    # collector-declared families: the master/volume scrape-time sources
    # plus the PR-3 self-observability collectors (trace ring, profiler)
    from seaweedfs_tpu.s3api.s3_server import S3Server
    from seaweedfs_tpu.server.filer import FilerServer

    from seaweedfs_tpu.qos import admission as qos_mod
    from seaweedfs_tpu.stats import aggregate as aggregate_mod
    from seaweedfs_tpu.stats import events as events_mod
    from seaweedfs_tpu.stats import heat as heat_mod
    from seaweedfs_tpu.stats import usage as usage_mod

    collector_names = sorted(
        set(MasterServer.MASTER_METRIC_FAMILIES)
        | set(VolumeServer.FL_FAMILIES)
        | set(FilerServer.FL_FRONT_FAMILIES)
        | set(S3Server.FL_FRONT_FAMILIES)
        | set(trace.TRACE_SELF_FAMILIES)
        | set(profiler.PROFILER_FAMILIES)
        | set(history.HISTORY_FAMILIES)
        | set(alerts.ALERT_FAMILIES)
        | set(alerts.SLO_FAMILIES)
        | set(events_mod.EVENT_FAMILIES)
        | set(maintenance.MAINTENANCE_FAMILIES)
        | set(usage_mod.USAGE_FAMILIES)
        | set(heat_mod.HEAT_FAMILIES)
        | set(heat_mod.ROLLUP_FAMILIES)
        | set(aggregate_mod.CLUSTER_FAMILIES)
        | set(qos_mod.QOS_FAMILIES)
    )
    return kinds, collector_names


def alert_rule_violations() -> list[str]:
    """Rule names become the `alert` label of SeaweedFS_alerts_firing and
    SeaweedFS_alerts_fired_total — lint them like metric names: unique
    snake_case, known severity."""
    from seaweedfs_tpu.stats import alerts

    rules = alerts.default_rules()
    bad: list[str] = []
    seen: set[str] = set()
    for r in rules:
        if not ALERT_RULE_RE.match(r.name):
            bad.append(f"alert rule {r.name!r}: not snake_case")
        if r.name in seen:
            bad.append(f"alert rule {r.name!r}: duplicate name")
        seen.add(r.name)
        if r.severity not in ALERT_SEVERITIES:
            bad.append(f"alert rule {r.name!r}: severity {r.severity!r}"
                       f" not in {sorted(ALERT_SEVERITIES)}")
    return bad


def task_type_violations() -> list[str]:
    """Maintenance task-type names become the `task` label of every
    SeaweedFS_maintenance_* family AND the detector/executor registry
    keys — lint them like alert-rule names: unique snake_case, with a
    detector and an executor actually registered for each."""
    from seaweedfs_tpu import maintenance

    bad: list[str] = []
    for name, spec in maintenance.TASK_TYPES.items():
        if not ALERT_RULE_RE.match(name):
            bad.append(f"maintenance task type {name!r}: not snake_case")
        if spec.name != name:
            bad.append(f"maintenance task type {name!r}: spec name"
                       f" mismatch ({spec.name!r})")
        if spec.concurrency < 1:
            bad.append(f"maintenance task type {name!r}: concurrency"
                       f" {spec.concurrency} < 1")
    for registry_name, registry in (
        ("detector", maintenance.DETECTORS),
        ("executor", maintenance.EXECUTORS),
    ):
        missing = set(maintenance.TASK_TYPES) ^ set(registry)
        for name in sorted(missing):
            bad.append(f"maintenance task type {name!r}: no matching"
                       f" {registry_name} registration")
    return bad


def front_reason_violations() -> list[str]:
    """Front-door fallback reasons ride into the `reason` label of the
    SeaweedFS_{filer,s3}_fastlane_fallback_total families — lint them
    (unique snake_case) and require the alert's pathological subset to be
    a real subset, so a renamed reason can't silently un-wire the
    fastlane_fallback rule."""
    from seaweedfs_tpu.storage import fastlane

    bad: list[str] = []
    seen: set[str] = set()
    for name in fastlane.FALLBACK_REASONS:
        if not ALERT_RULE_RE.match(name):
            bad.append(f"fallback reason {name!r}: not snake_case")
        if name in seen:
            bad.append(f"fallback reason {name!r}: duplicate")
        seen.add(name)
    for name in fastlane.PATHOLOGICAL_REASONS:
        if name not in seen:
            bad.append(f"pathological reason {name!r}: not a declared"
                       f" fallback reason")
    for name in fastlane.FRONT_OPS:
        if not ALERT_RULE_RE.match(name):
            bad.append(f"front op {name!r}: not snake_case")
    return bad


def ec_online_reason_violations() -> list[str]:
    """Online-EC degrade reasons ride into the `reason` label of
    SeaweedFS_volume_ec_online_fallbacks_total — lint them like the
    front-door reason set (unique snake_case, the pathological subset —
    what bench asserts is zero in steady state — must stay a real
    subset so a renamed reason can't silently pass the acceptance)."""
    from seaweedfs_tpu.storage.erasure_coding import online

    bad: list[str] = []
    seen: set[str] = set()
    for name in online.FALLBACK_REASONS:
        if not ALERT_RULE_RE.match(name):
            bad.append(f"ec_online fallback reason {name!r}: not snake_case")
        if name in seen:
            bad.append(f"ec_online fallback reason {name!r}: duplicate")
        seen.add(name)
    for name in online.PATHOLOGICAL_REASONS:
        if name not in seen:
            bad.append(f"ec_online pathological reason {name!r}: not a"
                       f" declared fallback reason")
    return bad


def fault_point_violations() -> list[str]:
    """Fault-point names become the `point` label of
    SeaweedFS_faults_injected_total AND the chaos suite's coverage
    contract — lint them: unique dotted lowercase, every DECLARED point
    registered by a real seam (importing the seam modules), and every
    point exercised by tests/test_chaos.py (a fault nobody injects in
    the suite is a fault nobody proved survivable)."""
    from seaweedfs_tpu.util import faults

    bad: list[str] = []
    seen: set[str] = set()
    for name in faults.ALL_POINTS:
        if not FAULT_POINT_RE.match(name):
            bad.append(f"fault point {name!r}: not dotted lowercase")
        if name in seen:
            bad.append(f"fault point {name!r}: duplicate")
        seen.add(name)
    # importing the seam modules registers their points; collect()
    # already pulled in the servers, but run standalone-safe here
    import seaweedfs_tpu.filer.wdclient  # noqa: F401
    import seaweedfs_tpu.server.master  # noqa: F401
    import seaweedfs_tpu.server.volume  # noqa: F401
    import seaweedfs_tpu.storage.erasure_coding.ec_volume  # noqa: F401
    import seaweedfs_tpu.storage.erasure_coding.online  # noqa: F401
    import seaweedfs_tpu.storage.fastlane  # noqa: F401
    import seaweedfs_tpu.storage.volume  # noqa: F401

    registered = set(faults.registered_points())
    for name in sorted(set(faults.ALL_POINTS) - registered):
        bad.append(f"fault point {name!r}: declared but no seam registers it")
    for name in sorted(registered - set(faults.ALL_POINTS)):
        bad.append(f"fault point {name!r}: registered but not declared")
    chaos = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tests", "test_chaos.py",
    )
    try:
        with open(chaos) as f:
            chaos_src = f.read()
    except OSError:
        return bad + ["tests/test_chaos.py missing: every fault point must"
                      " be exercised by the chaos suite"]
    for name in faults.ALL_POINTS:
        if name not in chaos_src:
            bad.append(f"fault point {name!r}: not exercised by"
                       f" tests/test_chaos.py")
    return bad


def event_type_violations() -> list[str]:
    """Flight-recorder event types (stats/events.py) become the `type`
    label of SeaweedFS_events_recorded_total, /debug/events' filter
    vocabulary, and cluster.why's timeline rows — lint them like the
    fault-point registry: unique snake_case, every DECLARED type emitted
    by a real seam somewhere in the package (an event nobody journals is
    a lie in the registry), and every type exercised by the tests
    (tests/test_events.py or tests/test_chaos.py)."""
    from seaweedfs_tpu.stats import events as events_mod

    bad: list[str] = []
    for name in events_mod.EVENT_TYPES:
        # (no duplicate check: EVENT_TYPES is a dict — the data type
        # already guarantees uniqueness)
        if not ALERT_RULE_RE.match(name):
            bad.append(f"event type {name!r}: not snake_case")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pkg = os.path.join(root, "seaweedfs_tpu")
    events_src = os.path.join("stats", "events.py")
    emitted: set[str] = set()
    for dirpath, _, files in os.walk(pkg):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            if path.endswith(events_src):
                continue  # the registry itself does not count as a seam
            try:
                with open(path) as f:
                    src = f.read()
            except OSError:
                continue
            for name in events_mod.EVENT_TYPES:
                if name in emitted:
                    continue
                if f'"{name}"' in src or f"'{name}'" in src:
                    emitted.add(name)
    for name in sorted(set(events_mod.EVENT_TYPES) - emitted):
        bad.append(f"event type {name!r}: declared but no seam emits it")
    test_src = ""
    for tf in ("test_events.py", "test_chaos.py"):
        try:
            with open(os.path.join(root, "tests", tf)) as f:
                test_src += f.read()
        except OSError:
            bad.append(f"tests/{tf} missing: the event registry must be"
                       f" exercised by the suite")
    for name in events_mod.EVENT_TYPES:
        if name not in test_src:
            bad.append(f"event type {name!r}: not exercised by"
                       f" tests/test_events.py or tests/test_chaos.py")
    return bad


def slo_violations() -> list[str]:
    """SLO names ride into the `slo` label of SeaweedFS_slo_burn_rate
    and the burn alerts' details — lint them like alert-rule names
    (unique snake_case, sane objectives, known kinds/roles), and require
    the two multi-window burn rules to exist with the right severities
    so a renamed rule can't silently un-page the fast burn."""
    from seaweedfs_tpu.stats import alerts

    bad: list[str] = []
    seen: set[str] = set()
    known_roles = {"master", "volume", "filer", "s3", "webdav"}
    for slo in alerts.DEFAULT_SLOS:
        if not ALERT_RULE_RE.match(slo.name):
            bad.append(f"slo {slo.name!r}: not snake_case")
        if slo.name in seen:
            bad.append(f"slo {slo.name!r}: duplicate name")
        seen.add(slo.name)
        if slo.kind not in ("availability", "latency"):
            bad.append(f"slo {slo.name!r}: unknown kind {slo.kind!r}")
        if not (0.0 < slo.objective < 1.0):
            bad.append(f"slo {slo.name!r}: objective {slo.objective}"
                       f" not in (0, 1)")
        if slo.kind == "latency" and slo.threshold_s <= 0:
            bad.append(f"slo {slo.name!r}: latency slo needs a positive"
                       f" threshold_s")
        if slo.role not in known_roles:
            bad.append(f"slo {slo.name!r}: unknown role {slo.role!r}")
    severities = {r.name: r.severity for r in alerts.default_rules()}
    if severities.get("slo_burn_fast") != "critical":
        bad.append("alert rule slo_burn_fast: missing or not critical")
    if severities.get("slo_burn_slow") != "warning":
        bad.append("alert rule slo_burn_slow: missing or not warning")
    return bad


def repair_reason_violations() -> list[str]:
    """Repair modes / fallback reasons / chain-restart reasons ride into
    the labels of the SeaweedFS_volume_ec_repair_* families (and the
    shell verb's -mode flag) — lint them like the other reason sets:
    unique snake_case, the restart reasons a real subset of the fallback
    reasons (a restart that exhausts becomes that fallback), and the
    mode set exactly the classic/pipelined pair bench compares."""
    from seaweedfs_tpu.storage.erasure_coding import decoder

    bad: list[str] = []
    if tuple(sorted(decoder.REPAIR_MODES)) != ("classic", "pipelined"):
        bad.append(f"repair modes {decoder.REPAIR_MODES!r}: expected"
                   f" exactly classic+pipelined")
    seen: set[str] = set()
    for name in decoder.REPAIR_FALLBACK_REASONS:
        if not ALERT_RULE_RE.match(name):
            bad.append(f"repair fallback reason {name!r}: not snake_case")
        if name in seen:
            bad.append(f"repair fallback reason {name!r}: duplicate")
        seen.add(name)
    for name in decoder.REPAIR_RESTART_REASONS:
        if name not in seen:
            bad.append(f"repair restart reason {name!r}: not a declared"
                       f" fallback reason")
    return bad


def stream_lazy_violations() -> list[str]:
    """The streaming-session chunk states (the `state` label of
    SeaweedFS_volume_ec_repair_stream_chunks_total) and the lazy-batch
    outcomes (the `outcome` label of
    SeaweedFS_maintenance_lazy_batch_total) — lint them like the other
    reason sets: unique snake_case, the streaming failure reasons
    (stream_stall, chunk_crc) declared restart reasons (so their
    exhaustion has a typed fallback), and the whole vocabulary exercised
    by the suite (a state nobody drives is a state nobody proved
    reachable)."""
    from seaweedfs_tpu.maintenance import scheduler as sched_mod
    from seaweedfs_tpu.storage.erasure_coding import decoder

    bad: list[str] = []
    for label, names in (
        ("stream chunk state", decoder.STREAM_CHUNK_STATES),
        ("lazy batch outcome", sched_mod.LAZY_OUTCOMES),
    ):
        seen: set[str] = set()
        for name in names:
            if not ALERT_RULE_RE.match(name):
                bad.append(f"{label} {name!r}: not snake_case")
            if name in seen:
                bad.append(f"{label} {name!r}: duplicate")
            seen.add(name)
    for reason in ("stream_stall", "chunk_crc"):
        if reason not in decoder.REPAIR_RESTART_REASONS:
            bad.append(f"streaming reason {reason!r}: not a declared"
                       f" restart reason")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    test_src = ""
    for tf in ("test_ec_repair.py", "test_maintenance.py",
               "test_chaos.py"):
        try:
            with open(os.path.join(root, "tests", tf)) as f:
                test_src += f.read()
        except OSError:
            bad.append(f"tests/{tf} missing: the streaming/lazy sets"
                       f" must be exercised by the suite")
    for name in ("stream_stall", "chunk_crc",
                 *decoder.STREAM_CHUNK_STATES, *sched_mod.LAZY_OUTCOMES):
        if name not in test_src:
            bad.append(f"streaming/lazy name {name!r}: not exercised by"
                       f" tests/test_ec_repair.py, test_maintenance.py"
                       f" or test_chaos.py")
    return bad


def scrub_violations() -> list[str]:
    """Scrub finding kinds ride into the `kind` label of
    SeaweedFS_volume_scrub_{findings,repairs}_total, the scrub_finding
    event's attrs and the scrub repair routing table — lint them like
    the other reason sets (unique snake_case), require the `corrupt`
    fault mode to exist AND be exercised by the chaos suite (silent
    damage nobody injects is silent damage nobody proved detectable),
    and require the `scrub` maintenance task type to be registered."""
    from seaweedfs_tpu import maintenance
    from seaweedfs_tpu.maintenance import scrub as scrub_mod
    from seaweedfs_tpu.util import faults

    bad: list[str] = []
    seen: set[str] = set()
    for name in scrub_mod.SCRUB_FINDING_KINDS:
        if not ALERT_RULE_RE.match(name):
            bad.append(f"scrub finding kind {name!r}: not snake_case")
        if name in seen:
            bad.append(f"scrub finding kind {name!r}: duplicate")
        seen.add(name)
    if "corrupt" not in faults.MODES:
        bad.append("fault mode 'corrupt' missing from faults.MODES"
                   " (scrub detection is untestable end to end)")
    if "scrub" not in maintenance.TASK_TYPES:
        bad.append("maintenance task type 'scrub' not registered")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    chaos_src, test_src = "", ""
    for tf, into in (("test_chaos.py", "chaos"), ("test_scrub.py", "unit")):
        try:
            with open(os.path.join(root, "tests", tf)) as f:
                src = f.read()
        except OSError:
            bad.append(f"tests/{tf} missing: the scrub subsystem must be"
                       f" exercised by the suite")
            continue
        test_src += src
        if into == "chaos":
            chaos_src = src
    if '"corrupt"' not in chaos_src and "'corrupt'" not in chaos_src:
        bad.append("fault mode 'corrupt': not exercised by"
                   " tests/test_chaos.py")
    for name in scrub_mod.SCRUB_FINDING_KINDS:
        if name not in test_src:
            bad.append(f"scrub finding kind {name!r}: not exercised by"
                       f" tests/test_scrub.py or tests/test_chaos.py")
    return bad


def degraded_reason_violations() -> list[str]:
    """Degraded-read reasons ride into the `reason` label of
    SeaweedFS_volume_degraded_reads_total (and the degraded_reads alert
    sums over them) — lint them like the other reason sets."""
    from seaweedfs_tpu.storage.volume import DEGRADED_READ_REASONS

    bad: list[str] = []
    seen: set[str] = set()
    for name in DEGRADED_READ_REASONS:
        if not ALERT_RULE_RE.match(name):
            bad.append(f"degraded-read reason {name!r}: not snake_case")
        if name in seen:
            bad.append(f"degraded-read reason {name!r}: duplicate")
        seen.add(name)
    return bad


def usage_heat_violations() -> list[str]:
    """The tenant/heat telemetry contract: every usage/heat family
    declared, the sketch's _other sentinel reserved (a real collection
    named `_other` would alias the overflow row), the three heat/usage
    event types registered, and the capacity-forecast alert pair present
    with the right severities — so a renamed gauge can't silently
    un-wire cluster.check's days-to-full failure mode."""
    from seaweedfs_tpu.stats import alerts
    from seaweedfs_tpu.stats import events as events_mod
    from seaweedfs_tpu.stats import heat as heat_mod
    from seaweedfs_tpu.stats import usage as usage_mod

    bad: list[str] = []
    for fam in (*usage_mod.USAGE_FAMILIES, *heat_mod.HEAT_FAMILIES,
                *heat_mod.ROLLUP_FAMILIES):
        if fam in SPECIAL_NAMES:
            continue
        if not NAME_RE.match(fam):
            bad.append(f"usage/heat family {fam!r}: does not match"
                       f" SeaweedFS_<subsystem>_<snake_case>")
    if not usage_mod.OTHER.startswith("_"):
        bad.append(f"usage overflow sentinel {usage_mod.OTHER!r}: must"
                   f" start with '_' (real collections are snake_case)")
    if usage_mod.DEFAULT_K < 1:
        bad.append(f"usage DEFAULT_K {usage_mod.DEFAULT_K}: must be >= 1")
    for ev in ("tenant_overflow", "heat_promoted", "heat_demoted"):
        if ev not in events_mod.EVENT_TYPES:
            bad.append(f"event type {ev!r}: missing from the flight"
                       f" recorder registry")
    severities = {r.name: r.severity for r in alerts.default_rules()}
    if severities.get("capacity_forecast") != "warning":
        bad.append("alert rule capacity_forecast: missing or not warning")
    if severities.get("capacity_forecast_critical") != "critical":
        bad.append("alert rule capacity_forecast_critical: missing or"
                   " not critical")
    return bad


def cluster_telemetry_violations() -> list[str]:
    """The cluster telemetry plane's contract (stats/aggregate.py): every
    `cluster` family well-formed, the staleness + self-observability
    families present (a renamed stale gauge would silently un-wire the
    "gateway went quiet" finding), and the cluster-scope alert rule names
    unique snake_case with known severities — they become the `alert`
    label of SeaweedFS_cluster_alerts_firing."""
    from seaweedfs_tpu.stats import aggregate as aggregate_mod

    bad: list[str] = []
    fams = aggregate_mod.CLUSTER_FAMILIES
    for fam in fams:
        if not NAME_RE.match(fam):
            bad.append(f"cluster family {fam!r}: does not match"
                       f" SeaweedFS_<subsystem>_<snake_case>")
        elif not fam.startswith("SeaweedFS_cluster_"):
            bad.append(f"cluster family {fam!r}: must live in the"
                       f" `cluster` subsystem")
    for required in ("SeaweedFS_cluster_telemetry_stale",
                     "SeaweedFS_cluster_telemetry_senders",
                     "SeaweedFS_cluster_telemetry_frames_total",
                     "SeaweedFS_cluster_telemetry_frame_age_seconds",
                     "SeaweedFS_cluster_usage_error_bound",
                     "SeaweedFS_cluster_slo_burn_rate",
                     "SeaweedFS_cluster_alerts_firing"):
        if required not in fams:
            bad.append(f"cluster family {required!r}: missing from"
                       f" CLUSTER_FAMILIES")
    seen: set[str] = set()
    for name, severity in aggregate_mod.CLUSTER_RULES:
        if name in seen:
            bad.append(f"cluster rule {name!r}: duplicate name")
        seen.add(name)
        if not name.startswith("cluster_"):
            bad.append(f"cluster rule {name!r}: must carry the cluster_"
                       f" prefix (dashboards must tell cluster-scope"
                       f" firing from per-process slo_burn_*)")
        if not ALERT_RULE_RE.match(name):
            bad.append(f"cluster rule {name!r}: not snake_case")
        if severity not in ALERT_SEVERITIES:
            bad.append(f"cluster rule {name!r}: severity {severity!r}"
                       f" not in {sorted(ALERT_SEVERITIES)}")
    return bad


def telemetry_violations() -> list[str]:
    """The durable-telemetry contract (stats/store.py): every spool
    family declared, in the `telemetry` subsystem, with the spool gauge
    + cap pair both present (the near-cap alert divides one by the
    other, so a renamed gauge would silently un-wire it), the flush and
    replay timers present, and the telemetry_spool_near_cap rule a
    warning — eviction is an ops heads-up, never an incident page."""
    from seaweedfs_tpu.stats import alerts
    from seaweedfs_tpu.stats import store as store_mod

    bad: list[str] = []
    fams = store_mod.TELEMETRY_FAMILIES
    for fam in fams:
        if not NAME_RE.match(fam):
            bad.append(f"telemetry family {fam!r}: does not match"
                       f" SeaweedFS_<subsystem>_<snake_case>")
        elif not fam.startswith("SeaweedFS_telemetry_"):
            bad.append(f"telemetry family {fam!r}: must live in the"
                       f" `telemetry` subsystem")
    for required in ("SeaweedFS_telemetry_spool_bytes",
                     "SeaweedFS_telemetry_spool_cap_bytes",
                     "SeaweedFS_telemetry_flush_seconds",
                     "SeaweedFS_telemetry_replay_seconds",
                     "SeaweedFS_telemetry_segments_evicted_total"):
        if required not in fams:
            bad.append(f"telemetry family {required!r}: missing from"
                       f" TELEMETRY_FAMILIES")
    tiers = {t for t, _, _ in store_mod.TIERS}
    for required_tier in ("raw", "1m", "10m", "events"):
        if required_tier not in tiers:
            bad.append(f"telemetry tier {required_tier!r}: missing from"
                       f" store.TIERS (the spool gauge's tier label set)")
    shares = sum(share for _, _, share in store_mod.TIERS)
    if not 0.99 <= shares <= 1.01:
        bad.append(f"telemetry tier shares sum to {shares:g}: the"
                   f" -telemetry.retention budget must be fully carved")
    severities = {r.name: r.severity for r in alerts.default_rules()}
    if severities.get("telemetry_spool_near_cap") != "warning":
        bad.append("alert rule telemetry_spool_near_cap: missing or"
                   " not warning")
    return bad


def qos_violations() -> list[str]:
    """The admission-control contract (qos/admission.py): every QoS
    family declared in the `qos` subsystem, the shed-reason and
    priority-class vocabularies closed (unique snake_case — they become
    the `reason`/`class` labels of SeaweedFS_qos_shed_total and the
    machine-readable 429/503 bodies clients retry on), every reason
    mapped to a 429 or 503, the qos_shed event registered AND emitted
    by the admission seam, and the qos_shed_interactive rule critical —
    sustained interactive-class shedding is exactly what cluster.check
    -fail must exit nonzero on."""
    from seaweedfs_tpu.qos import admission as qos_mod
    from seaweedfs_tpu.stats import alerts
    from seaweedfs_tpu.stats import events as events_mod

    bad: list[str] = []
    for fam in qos_mod.QOS_FAMILIES:
        if not NAME_RE.match(fam):
            bad.append(f"qos family {fam!r}: does not match"
                       f" SeaweedFS_<subsystem>_<snake_case>")
        elif not fam.startswith("SeaweedFS_qos_"):
            bad.append(f"qos family {fam!r}: must live in the `qos`"
                       f" subsystem")
    for required in ("SeaweedFS_qos_admitted_total",
                     "SeaweedFS_qos_shed_total",
                     "SeaweedFS_qos_queued_total"):
        if required not in qos_mod.QOS_FAMILIES:
            bad.append(f"qos family {required!r}: missing from"
                       f" QOS_FAMILIES")
    for label, names in (
        ("qos shed reason", qos_mod.SHED_REASONS),
        ("qos priority class", qos_mod.PRIORITY_CLASSES),
    ):
        seen: set[str] = set()
        for name in names:
            if not ALERT_RULE_RE.match(name):
                bad.append(f"{label} {name!r}: not snake_case")
            if name in seen:
                bad.append(f"{label} {name!r}: duplicate")
            seen.add(name)
    for reason in qos_mod.SHED_REASONS:
        status = qos_mod._REASON_STATUS.get(reason)
        if status not in (429, 503):
            bad.append(f"qos shed reason {reason!r}: no 429/503 status"
                       f" mapping (clients can't type the rejection)")
    for reason in qos_mod._REASON_STATUS:
        if reason not in qos_mod.SHED_REASONS:
            bad.append(f"qos status mapping {reason!r}: not a declared"
                       f" shed reason")
    if "qos_shed" not in events_mod.EVENT_TYPES:
        bad.append("event type 'qos_shed': missing from the flight"
                   " recorder registry")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    adm = os.path.join(root, "seaweedfs_tpu", "qos", "admission.py")
    try:
        with open(adm) as f:
            adm_src = f.read()
    except OSError:
        adm_src = ""
    if '"qos_shed"' not in adm_src and "'qos_shed'" not in adm_src:
        bad.append("event type 'qos_shed': not emitted by"
                   " qos/admission.py (the shed seam must journal)")
    severities = {r.name: r.severity for r in alerts.default_rules()}
    if severities.get("qos_shed_interactive") != "critical":
        bad.append("alert rule qos_shed_interactive: missing or not"
                   " critical")
    return bad


def violations(kinds: dict[str, str], collector_names: list[str]) -> list[str]:
    bad: list[str] = []
    for name in sorted(set(kinds) | set(collector_names)):
        if name in SPECIAL_NAMES:
            continue
        if not NAME_RE.match(name):
            bad.append(f"{name}: does not match "
                       "SeaweedFS_<subsystem>_<snake_case>")
    for name, kind in sorted(kinds.items()):
        if kind == "counter" and not name.endswith("_total"):
            bad.append(f"{name}: counter must end in _total")
        elif kind == "histogram" and not name.endswith(HISTOGRAM_UNITS):
            bad.append(f"{name}: histogram must end in a base unit "
                       f"({'/'.join(HISTOGRAM_UNITS)})")
        elif kind == "gauge" and name.endswith("_total"):
            bad.append(f"{name}: gauge must not end in _total")
    return bad


def main() -> int:
    kinds, collector_names = collect()
    bad = violations(kinds, collector_names) + alert_rule_violations() \
        + task_type_violations() + front_reason_violations() \
        + ec_online_reason_violations() + fault_point_violations() \
        + degraded_reason_violations() + repair_reason_violations() \
        + stream_lazy_violations() \
        + event_type_violations() + slo_violations() + scrub_violations() \
        + usage_heat_violations() + cluster_telemetry_violations() \
        + telemetry_violations() + qos_violations()
    total = len(set(kinds) | set(collector_names))
    if bad:
        print(f"{len(bad)} metric-name violation(s) in {total} families:")
        for b in bad:
            print(f"  {b}")
        return 1
    print(f"{total} metric families OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
