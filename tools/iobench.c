/* iobench: measure single-core IO strategies on tmpfs for the EC encoder.
 *
 * Usage: iobench <dir> [mb]
 * Prints one line per strategy: name MB/s.
 */
#define _GNU_SOURCE
#include <fcntl.h>
#include <immintrin.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/time.h>
#include <sys/uio.h>
#include <time.h>
#include <unistd.h>

static double now(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + ts.tv_nsec * 1e-9;
}

static void report(const char *name, size_t bytes, double dt) {
  printf("%-28s %8.2f GB/s  (%.4fs)\n", name, bytes / dt / 1e9, dt);
}

int main(int argc, char **argv) {
  const char *dir = argc > 1 ? argv[1] : "/dev/shm";
  size_t mb = argc > 2 ? (size_t)atol(argv[2]) : 1024;
  size_t total = mb << 20;
  char src_path[4096], dst_path[4096];
  snprintf(src_path, sizeof src_path, "%s/iobench.src", dir);
  snprintf(dst_path, sizeof dst_path, "%s/iobench.dst", dir);

  /* build source file */
  int sfd = open(src_path, O_RDWR | O_CREAT, 0644);
  if (sfd < 0) { perror("open src"); return 1; }
  if (ftruncate(sfd, total)) { perror("trunc"); return 1; }
  size_t chunk = 64 << 20;
  unsigned char *buf;
  if (posix_memalign((void **)&buf, 4096, chunk)) return 1;
  for (size_t i = 0; i < chunk; i++) buf[i] = (unsigned char)(i * 2654435761u >> 24);
  for (size_t off = 0; off < total; off += chunk)
    if (pwrite(sfd, buf, chunk, off) != (ssize_t)chunk) { perror("pw"); return 1; }

  int dfd = open(dst_path, O_RDWR | O_CREAT, 0644);
  if (ftruncate(dfd, total)) { perror("trunc dst"); return 1; }
  /* prewarm dst pages */
  for (size_t off = 0; off < total; off += chunk) pwrite(dfd, buf, chunk, off);

  double t0, dt;
  volatile uint64_t sink = 0;

  /* 1. memcpy user->user */
  unsigned char *buf2; posix_memalign((void **)&buf2, 4096, chunk);
  memcpy(buf2, buf, chunk); /* warm */
  t0 = now();
  for (int i = 0; i < 16; i++) memcpy(buf2, buf, chunk);
  report("memcpy(64MB x16)", chunk * 16, now() - t0);

  /* 2. pread existing tmpfs -> buf */
  t0 = now();
  for (size_t off = 0; off < total; off += chunk)
    if (pread(sfd, buf, chunk, off) != (ssize_t)chunk) { perror("pr"); return 1; }
  report("pread 64MB chunks", total, now() - t0);

  /* 2b. pread 1MB chunks */
  t0 = now();
  for (size_t off = 0; off < total; off += (1<<20))
    if (pread(sfd, buf, 1<<20, off) != (1<<20)) { perror("pr1m"); return 1; }
  report("pread 1MB chunks", total, now() - t0);

  /* 3. pwrite buf -> existing tmpfs */
  t0 = now();
  for (size_t off = 0; off < total; off += chunk)
    if (pwrite(dfd, buf, chunk, off) != (ssize_t)chunk) { perror("pw2"); return 1; }
  report("pwrite existing 64MB", total, now() - t0);

  t0 = now();
  for (size_t off = 0; off < total; off += (1<<20))
    if (pwrite(dfd, buf, 1<<20, off) != (1<<20)) { perror("pw1m"); return 1; }
  report("pwrite existing 1MB", total, now() - t0);

  /* 3b. pwrite to FRESH tmpfs file (page alloc cost) */
  int ffd = open(dst_path, O_RDWR | O_CREAT | O_TRUNC, 0644);
  t0 = now();
  for (size_t off = 0; off < total; off += chunk)
    if (pwrite(ffd, buf, chunk, off) != (ssize_t)chunk) { perror("pwf"); return 1; }
  report("pwrite fresh 64MB", total, now() - t0);
  close(ffd);

  /* 4. copy_file_range src -> existing dst */
  t0 = now();
  for (size_t off = 0; off < total; off += chunk) {
    loff_t in = off, out = off;
    ssize_t n = copy_file_range(sfd, &in, dfd, &out, chunk, 0);
    if (n != (ssize_t)chunk) { fprintf(stderr, "cfr: %zd\n", n); break; }
  }
  report("copy_file_range 64MB", total, now() - t0);

  /* 4b. copy_file_range 1MB pieces (shard-block granularity) */
  t0 = now();
  for (size_t off = 0; off < total; off += (1<<20)) {
    loff_t in = off, out = off;
    if (copy_file_range(sfd, &in, dfd, &out, 1<<20, 0) != (1<<20)) { perror("cfr1m"); break; }
  }
  report("copy_file_range 1MB", total, now() - t0);

  /* 5. mmap src MAP_POPULATE, stream-read */
  t0 = now();
  unsigned char *sm = mmap(NULL, total, PROT_READ, MAP_SHARED | MAP_POPULATE, sfd, 0);
  if (sm == MAP_FAILED) { perror("mmap src"); return 1; }
  dt = now() - t0;
  printf("%-28s %8.4f s  (populate %zuMB read map)\n", "mmap+POPULATE src", dt, mb);
  t0 = now();
  uint64_t acc = 0;
  for (size_t i = 0; i < total; i += 64) acc += *(const uint64_t *)(sm + i);
  sink = acc;
  report("mmap read touch (cached)", total, now() - t0);

  /* 6. mmap dst existing, populate-write, then NT stores */
  t0 = now();
  unsigned char *dm = mmap(NULL, total, PROT_READ | PROT_WRITE, MAP_SHARED | MAP_POPULATE, dfd, 0);
  if (dm == MAP_FAILED) { perror("mmap dst"); return 1; }
  dt = now() - t0;
  printf("%-28s %8.4f s  (populate %zuMB write map)\n", "mmap+POPULATE dst", dt, mb);

  /* first-touch write pass (page_mkwrite faults if any) */
  t0 = now();
  for (size_t i = 0; i < total; i += 4096) dm[i] = 1;
  printf("%-28s %8.4f s  (4K touch writes over %zuMB)\n", "mmap dst touch-write", now() - t0, mb);

  /* NT store full pass from L3-hot buf */
  t0 = now();
  for (size_t off = 0; off < total; off += chunk) {
    for (size_t i = 0; i < chunk; i += 64) {
      __m512i v = _mm512_load_si512(buf + i);
      _mm512_stream_si512((__m512i *)(dm + off + i), v);
    }
  }
  _mm_sfence();
  report("mmap NT-store pass", total, now() - t0);

  /* regular store pass */
  t0 = now();
  for (size_t off = 0; off < total; off += chunk) memcpy(dm + off, buf, chunk);
  report("mmap memcpy store pass", total, now() - t0);

  /* 7. read from src map + NT store to dst map (the fused pattern, no GF) */
  t0 = now();
  for (size_t i = 0; i < total; i += 64) {
    __m512i v = _mm512_load_si512(sm + i);
    _mm512_stream_si512((__m512i *)(dm + i), v);
  }
  _mm_sfence();
  report("map->map NT copy", total, now() - t0);

  /* 8. fresh-mmap fault cost on tmpfs with existing pages: remap + touch */
  munmap(dm, total);
  t0 = now();
  dm = mmap(NULL, total, PROT_READ | PROT_WRITE, MAP_SHARED, dfd, 0);
  for (size_t i = 0; i < total; i += 4096) dm[i] = 2;
  printf("%-28s %8.4f s  (no-populate fault+write all pages)\n", "mmap fresh fault-write", now() - t0);

  (void)sink;
  munmap(sm, total); munmap(dm, total);
  close(sfd); close(dfd);
  unlink(src_path); unlink(dst_path);
  return 0;
}
